#!/usr/bin/env python
"""Summarize a TelemetryHub JSONL file (the ``jsonl_monitor`` sink).

Reads ``events.jsonl`` lines of ``{"name", "value", "step", "ts"}`` and prints
a step-time / comm-volume / memory summary table — the offline companion to
the live ``log_summary()`` output. Deliberately free of jax/numpy imports so
it runs anywhere a telemetry file lands.

Usage: python scripts/telemetry_report.py runs/job/events.jsonl [--last N]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from collections import OrderedDict
from typing import Dict, List


def load_events(*paths: str) -> List[dict]:
    """Load one or more JSONL telemetry files, tolerating the torn tail a
    crash or SIGKILL leaves behind: an unparseable FINAL line is silently
    dropped (that is what a mid-``write(2)`` kill looks like), unparseable
    lines elsewhere are dropped with a stderr warning, and undecodable bytes
    never abort the load. The surviving events still make a full report.

    With MULTIPLE paths (a fleet of per-replica monitor files) the streams
    are concatenated in argument order and every record is provenance-tagged
    with ``"source"`` (the path, disambiguated to its shortest unique
    suffix) so the ``--fleet`` report can say which replica said what. A
    single path keeps the historical untagged record shape."""
    tag = len(paths) > 1
    labels = _source_labels(paths) if tag else {}
    events = []
    for path in paths:
        bad: List[int] = []
        n_lines = 0
        with open(path, encoding="utf-8", errors="replace") as f:
            for n_lines, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad.append(n_lines)
                    continue
                if isinstance(rec, dict) and "name" in rec and "value" in rec:
                    if tag:
                        rec["source"] = labels[path]
                    events.append(rec)
        interior = [n for n in bad if n != n_lines]
        if interior:
            print(f"warning: skipped {len(interior)} unparseable interior "
                  f"line(s) in {path} (first at line {interior[0]})",
                  file=sys.stderr)
    return events


def _source_labels(paths) -> Dict[str, str]:
    """Shortest-unique-suffix label per path: a fleet's files are usually
    ``.../replica0/events.jsonl`` vs ``.../replica1/events.jsonl``, where
    the basename alone would collide."""
    out: Dict[str, str] = {}
    for path in paths:
        parts = path.replace(os.sep, "/").split("/")
        for k in range(1, len(parts) + 1):
            label = "/".join(parts[-k:])
            others = [p for p in paths if p != path]
            if all(not p.replace(os.sep, "/").endswith(label)
                   for p in others):
                break
        out[path] = label
    return out


def _series(events: List[dict]) -> "OrderedDict[str, List[dict]]":
    by_name: "OrderedDict[str, List[dict]]" = OrderedDict()
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    return by_name


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:,.1f} {unit}"
        n /= 1024
    return f"{n:,.1f} TiB"


def comm_efficiency(events: List[dict]) -> str:
    """``--comm-efficiency``: collective count, total algorithmic bytes, and
    bytes-per-step from the ``Comm/*`` series — the offline comm-volume
    regression check (comm records are per compiled step, so the last sample
    of each series IS the per-step number; totals scale by executed steps)."""
    steps = sorted({e.get("step", 0) for e in events})
    n_steps = len(steps)
    per_op: Dict[str, Dict[str, float]] = {}
    for e in events:
        name = e["name"]
        if not name.startswith("Comm/") or name.startswith("Comm/total/") \
                or name.startswith("Comm/ring/"):
            continue  # ring schedule gauges get their own section below
        _, op, kind = name.split("/", 2)
        per_op.setdefault(op, {})[kind] = e["value"]  # last sample wins
    if not per_op:
        # no collectives recorded — the ring/overlap/remat/attn gauge
        # sections can still render (bench probes emit them without a
        # comms logger; ring fallback markers record even when disabled)
        extra = _ring_section(events) + _overlap_remat_sections(events)
        if extra:
            return "\n".join(extra)
        return "comm efficiency: no Comm/* events in this file"
    lines = [f"comm efficiency ({n_steps} steps)"]
    lines.append(f"  {'op':<28} {'count/step':>10} {'bytes/step':>14} "
                 f"{'algo bytes/step':>16}")
    tot_count = tot_bytes = tot_algo = 0.0
    for op, kinds in sorted(per_op.items()):
        count = kinds.get("count", 0.0)
        nbytes = kinds.get("bytes", 0.0)
        algo = kinds.get("algo_bytes", nbytes)
        tot_count += count
        tot_bytes += nbytes
        tot_algo += algo
        lines.append(f"  {op:<28} {int(count):>10} "
                     f"{_fmt_bytes(nbytes):>14} {_fmt_bytes(algo):>16}")
    lines.append(f"  {'TOTAL':<28} {int(tot_count):>10} "
                 f"{_fmt_bytes(tot_bytes):>14} {_fmt_bytes(tot_algo):>16}")
    lines.append("")
    lines.append(f"  collectives/step:      {int(tot_count)}")
    lines.append(f"  algo bytes/step:       {_fmt_bytes(tot_algo)}")
    lines.append(f"  algo bytes whole run:  {_fmt_bytes(tot_algo * n_steps)}")
    busbw = [e["value"] for e in events
             if e["name"] == "Comm/total/busbw_gbps"]
    if busbw:
        lines.append(f"  busbw (last):          {busbw[-1]:.2f} GB/s")
    frac = [e["value"] for e in events
            if e["name"] == "Comm/total/est_comm_frac"]
    if frac:
        lines.append(f"  est unoverlapped comm: {frac[-1] * 100:.1f}% "
                     f"of step time (upper bound)")
    quant = _quantized_comm_section(per_op, events)
    if quant:
        lines.append("")
        lines.extend(quant)
    ring = _ring_section(events)
    if ring:
        lines.append("")
        lines.extend(ring)
    extra = _overlap_remat_sections(events)
    if extra:
        lines.append("")
        lines.extend(extra)
    return "\n".join(lines)


def _ring_section(events: List[dict]) -> List[str]:
    """Ring-attention schedule rollup (``Comm/ring/*`` — sequence/ring.py,
    docs/performance.md "Million-token context"): KV-rotation hops/bytes,
    the active layout/overlap knobs, the measured compute↔transfer overlap
    fraction, and the silent-dense-fallback marker (nonzero = a ring entry
    point ran WITHOUT a seq axis and silently densified — fix the mesh)."""
    ring: Dict[str, float] = {}
    for e in events:
        if e["name"].startswith("Comm/ring/"):
            ring[e["name"].rsplit("/", 1)[-1]] = e["value"]  # last wins
    if not ring:
        return []
    lines = ["ring attention (Comm/ring/*)"]
    if "hops" in ring:
        lines.append(f"  KV-rotation hops:      {int(ring['hops'])}")
    if "bytes" in ring:
        lines.append(f"  KV bytes rotated:      {_fmt_bytes(ring['bytes'])}")
    if "zigzag" in ring:
        layout = "zigzag" if ring["zigzag"] else "contiguous"
        lines.append(f"  causal layout:         {layout}")
    if "overlap_on" in ring:
        lines.append(f"  overlap pipelining:    "
                     f"{'on' if ring['overlap_on'] else 'off'}")
    if "overlap_frac" in ring:
        lines.append(f"  measured overlap:      "
                     f"{ring['overlap_frac'] * 100:.1f}% of transfer hidden "
                     f"under compute")
    if ring.get("dense_fallback"):
        lines.append(f"  DENSE FALLBACK:        {int(ring['dense_fallback'])} "
                     f"call(s) ran without a seq axis (no ring executed)")
    return lines


def _quantized_comm_section(per_op: Dict[str, Dict[str, float]],
                            events: List[dict]) -> List[str]:
    """Quantized & hierarchical collectives rollup (ZeRO++ qwZ/qgZ/hpZ +
    EQuARX — docs/performance.md): per-path bytes-on-wire vs the fp32
    equivalent of the same payload (``Comm/<op>/fp32_equiv_bytes``) with the
    resulting compression ratio, plus the DCN-vs-ICI byte split from the
    per-collective link-class tag. Only rendered when at least one path
    actually compressed (ratio > 1.05) or a DCN split exists."""
    rows = []
    for op, kinds in sorted(per_op.items()):
        wire = kinds.get("bytes", 0.0)
        equiv = kinds.get("fp32_equiv_bytes", 0.0)
        if wire > 0 and equiv > wire * 1.05:
            rows.append((op, wire, equiv, equiv / wire))
    dcn = [e["value"] for e in events
           if e["name"] == "Comm/total/algo_bytes_dcn"]
    ici = [e["value"] for e in events
           if e["name"] == "Comm/total/algo_bytes_ici"]
    if not dcn:  # fall back to the per-op link split
        s = sum(k.get("algo_bytes_dcn", 0.0) for k in per_op.values())
        dcn = [s] if s else []
        ici = [sum(k.get("algo_bytes_ici", 0.0) for k in per_op.values())]
    has_dcn = bool(dcn and dcn[-1] > 0)
    if not rows and not has_dcn:
        return []
    lines = ["quantized & hierarchical collectives"]
    if rows:
        lines.append(f"  {'path':<28} {'wire bytes':>14} {'fp32 equiv':>14} "
                     f"{'ratio':>7}")
        for op, wire, equiv, ratio in rows:
            lines.append(f"  {op:<28} {_fmt_bytes(wire):>14} "
                         f"{_fmt_bytes(equiv):>14} {ratio:>6.2f}x")
    if dcn:
        total = (dcn[-1] if dcn else 0.0) + (ici[-1] if ici else 0.0)
        pct = dcn[-1] / total * 100 if total else 0.0
        lines.append(f"  DCN algo bytes/step:   {_fmt_bytes(dcn[-1])} "
                     f"({pct:.1f}% of total)")
        if ici:
            lines.append(f"  ICI algo bytes/step:   {_fmt_bytes(ici[-1])}")
    return lines


def _overlap_remat_sections(events: List[dict]) -> List[str]:
    """Fine-grained overlap + selective-remat + native-GQA rollup (the
    ``Train/overlap/*``, ``Train/remat/*`` and ``Train/attn/*`` gauge
    series — docs/performance.md): layer-prefetch configuration,
    overlap-hidden comm fraction, the per-remat-policy saved-bytes /
    peak-HBM / step-time sweep rows, and the narrow-KV attention traffic
    accounting. Gauges: last sample per series wins."""
    ov = {e["name"][len("Train/overlap/"):]: e["value"] for e in events
          if e["name"].startswith("Train/overlap/")}
    remat = {e["name"][len("Train/remat/"):]: e["value"] for e in events
             if e["name"].startswith("Train/remat/")}
    attn = {e["name"][len("Train/attn/"):]: e["value"] for e in events
            if e["name"].startswith("Train/attn/")}
    lines: List[str] = []
    if attn:
        lines.append("native GQA attention (attention.gqa_native)")
        if "gqa_ratio" in attn:
            lines.append(f"  query/kv head ratio:   "
                         f"{attn['gqa_ratio']:.0f}x")
        if "kv_bytes_saved" in attn:
            lines.append(f"  KV bytes saved/step:   "
                         f"{_fmt_bytes(attn['kv_bytes_saved'])} "
                         f"(fwd+bwd, vs widened kernels)")
        lines.append("")
    if ov:
        lines.append("fine-grained overlap (layer prefetch)")
        if "prefetch_depth" in ov:
            lines.append(f"  prefetch depth:        "
                         f"{int(ov['prefetch_depth'])} layer(s) in flight")
        if "prefetch_layers" in ov:
            lines.append(f"  prefetched layers:     "
                         f"{int(ov['prefetch_layers'])} per step")
        if "prefetch_bytes" in ov:
            lines.append(f"  gathered bytes/step:   "
                         f"{_fmt_bytes(ov['prefetch_bytes'])}")
        if "hidden_comm_frac" in ov:
            lines.append(f"  overlap-hidden comm:   "
                         f"{ov['hidden_comm_frac'] * 100:.1f}% of serial "
                         f"comm time (lower bound)")
    if remat:
        # names are <metric>_<policy>; metrics are fixed, policies open-ended
        per_policy: Dict[str, Dict[str, float]] = {}
        for key, val in remat.items():
            for metric in ("saved_bytes", "peak_bytes", "step_ms"):
                if key.startswith(metric + "_"):
                    per_policy.setdefault(key[len(metric) + 1:],
                                          {})[metric] = val
                    break
        if per_policy:
            if lines:
                lines.append("")
            lines.append("selective remat sweep (per policy)")
            lines.append(f"  {'policy':<22} {'saved bytes':>14} "
                         f"{'peak HBM':>14} {'step ms':>10}")
            for pol, m in sorted(per_policy.items()):
                saved = (_fmt_bytes(m["saved_bytes"])
                         if "saved_bytes" in m else "-")
                peak = (_fmt_bytes(m["peak_bytes"])
                        if "peak_bytes" in m else "-")
                step = (f"{m['step_ms']:.2f}" if "step_ms" in m else "-")
                lines.append(f"  {pol:<22} {saved:>14} {peak:>14} "
                             f"{step:>10}")
    return lines


def compile_report(events: List[dict]) -> str:
    """``--compile``: recompilation-sentinel counters per jitted program
    (compiles, cache hits, RECOMPILES, lowering/compile wall time, analytic
    cost-model flops) from the ``Compile/*`` stream, plus the per-program
    MFU attribution from ``Train/mfu/*`` / ``Serving/mfu/*`` — the
    decomposition of the ThroughputTimer headline (docs/observability.md).
    Cumulative counters and gauges: last sample per series wins."""
    comp = [e for e in events if e["name"].startswith("Compile/")]
    mfu = [e for e in events
           if e["name"].startswith(("Train/mfu/", "Serving/mfu/"))]
    if not comp and not mfu:
        return "compile: no Compile/* or */mfu/* events in this file"
    lines: List[str] = []
    if comp:
        per: Dict[str, Dict[str, float]] = {}
        for e in comp:
            _, prog, metric = e["name"].split("/", 2)
            per.setdefault(prog, {})[metric] = e["value"]   # last wins
        tot = per.pop("total", {})
        lines.append(f"compile report ({len(comp)} events)")
        lines.append(f"  {'program':<18} {'compiles':>8} {'hits':>8} "
                     f"{'recompiles':>10} {'compile ms':>11} "
                     f"{'cost flops':>12}")
        for prog in sorted(per):
            m = per[prog]
            fl = m.get("cost_flops", 0.0)
            fl_s = f"{fl:>12.3e}" if fl else f"{'-':>12}"
            lines.append(
                f"  {prog:<18} {int(m.get('compiles', 0)):>8} "
                f"{int(m.get('cache_hits', 0)):>8} "
                f"{int(m.get('recompiles', 0)):>10} "
                f"{m.get('compile_ms', 0.0):>11.1f} {fl_s}")
        lines.append("")
        recompiles = int(tot.get("recompiles", 0))
        lines.append(f"  programs:               "
                     f"{int(tot.get('programs', len(per)))}")
        lines.append(f"  total compiles:         "
                     f"{int(tot.get('compiles', 0))}")
        lines.append(f"  total recompiles:       {recompiles}"
                     + ("  <-- recompilation storm suspect"
                        if recompiles > int(tot.get("programs", 0)) else ""))
        lines.append(f"  compile wall time:      "
                     f"{tot.get('compile_ms', 0.0) / 1e3:.2f} s "
                     f"(+ {tot.get('lower_ms', 0.0) / 1e3:.2f} s lowering)")
    if mfu:
        last: Dict[str, float] = {}
        for e in mfu:
            last[e["name"]] = e["value"]                     # last wins
        if lines:
            lines.append("")
        lines.append("per-program MFU attribution (fraction of peak)")
        total = last.pop("Train/mfu/total", None)
        headline = last.pop("Train/mfu/headline", None)
        for name in sorted(last):
            prog = name.split("/", 2)[2]
            group = name.split("/", 1)[0].lower()
            lines.append(f"  {group + '/' + prog:<26} {last[name]:>8.4f}")
        if total is not None:
            lines.append(f"  {'TOTAL (attributed)':<26} {total:>8.4f}")
        if headline is not None:
            lines.append(f"  {'ThroughputTimer headline':<26} "
                         f"{headline:>8.4f}")
        if total and headline:
            lines.append(f"  attribution covers      "
                         f"{total / headline * 100:.1f}% of the headline")
    return "\n".join(lines)


def _load_anomaly_module():
    """Load ``deepspeed_tpu/telemetry/anomaly.py`` by file path (it is
    stdlib-only) so the offline replay needs no jax/numpy import; None when
    the report runs detached from the repo tree."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deepspeed_tpu", "telemetry", "anomaly.py")
    try:
        spec = importlib.util.spec_from_file_location("_dstpu_anomaly", path)
        mod = importlib.util.module_from_spec(spec)
        # dataclass construction resolves string annotations through
        # sys.modules — a by-path module must be registered first
        sys.modules["_dstpu_anomaly"] = mod
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        sys.modules.pop("_dstpu_anomaly", None)
        return None


def anomalies(events: List[dict]) -> str:
    """``--anomalies``: live ``Anomaly/*`` findings recorded by the hub's
    detector (spikes, drift, stragglers — count, worst excess, last step),
    plus an OFFLINE replay of the same rolling-median/MAD detector over the
    file's ``Train/Step/*_ms`` series, so a run recorded without the
    detector enabled can still be screened post-hoc."""
    rec = [e for e in events if e["name"].startswith("Anomaly/")]
    lines: List[str] = []
    if rec:
        per: Dict[str, Dict[str, float]] = {}
        for e in rec:
            d = per.setdefault(e["name"][len("Anomaly/"):],
                               {"count": 0, "worst": 0.0, "last_step": 0})
            d["count"] += 1
            d["worst"] = max(d["worst"], float(e["value"]))
            d["last_step"] = max(d["last_step"], int(e.get("step", 0)))
        lines.append(f"anomaly report ({len(rec)} recorded findings)")
        lines.append(f"  {'finding':<28} {'count':>6} {'worst excess':>13} "
                     f"{'last step':>10}")
        for key in sorted(per):
            d = per[key]
            lines.append(f"  {key:<28} {d['count']:>6} "
                         f"{d['worst'] * 100:>12.0f}% {d['last_step']:>10}")
    else:
        lines.append("anomaly report: no recorded Anomaly/* findings")
    mod = _load_anomaly_module()
    phase = OrderedDict()
    for e in events:
        n = e["name"]
        if n.startswith("Train/Step/") and n.endswith("_ms"):
            phase.setdefault(n[len("Train/Step/"):-len("_ms")],
                             []).append(e)
    if mod is None:
        lines.append("  (offline replay unavailable: telemetry/anomaly.py "
                     "not found next to this script)")
        return "\n".join(lines)
    if not phase:
        lines.append("  (no Train/Step/*_ms series to replay — record with "
                     "wall_clock_breakdown: true)")
        return "\n".join(lines)
    det = mod.AnomalyDetector(mod.AnomalyConfig(enabled=True))
    findings = []
    for key, recs in phase.items():
        series = "step_time" if key == "train_batch" else f"phase/{key}"
        for r in recs:
            findings += det.observe(series, float(r["value"]),
                                    int(r.get("step", 0)))
    n_samples = sum(len(v) for v in phase.values())
    lines.append("")
    lines.append(f"offline replay over {len(phase)} step-time series "
                 f"({n_samples} samples): {len(findings)} finding(s)")
    for f in findings[:20]:
        lines.append(f"  [{f.series}] {f.detail}")
    if len(findings) > 20:
        lines.append(f"  ... {len(findings) - 20} more")
    return "\n".join(lines)


def reliability(events: List[dict]) -> str:
    """``--reliability``: skipped steps, watchdog events, and checkpoint
    save/restore/rollback counts from the ``Reliability/*`` event stream
    (reliability subsystem — docs/reliability.md). Each event is one
    occurrence; counts are event-line counts, not value sums."""
    rel = [e for e in events if e["name"].startswith("Reliability/")]
    if not rel:
        return "reliability: no Reliability/* events in this file"
    counts: Dict[str, int] = {}
    last_step: Dict[str, int] = {}
    for e in rel:
        key = e["name"][len("Reliability/"):]
        counts[key] = counts.get(key, 0) + 1
        last_step[key] = max(last_step.get(key, 0), int(e.get("step", 0)))
    lines = [f"reliability report ({len(rel)} events)"]
    lines.append(f"  {'event':<28} {'count':>6} {'last step':>10}")
    for key in sorted(counts):
        lines.append(f"  {key:<28} {counts[key]:>6} {last_step[key]:>10}")
    lines.append("")

    def total(*keys: str) -> int:
        return sum(counts.get(k, 0) for k in keys)

    violations = total(*[k for k in counts if k.startswith("violation/")])
    lines.append(f"  checkpoint saves:       {total('checkpoint_saved')}")
    lines.append(f"  checkpoint loads:       {total('checkpoint_loaded')}")
    lines.append(f"  rollbacks (walk-back):  {total('checkpoint_rollback')}")
    lines.append(f"  auto-restores:          {total('auto_restore')}")
    lines.append(f"  I/O retries:            {total('checkpoint_io_retry')}")
    lines.append(f"  GC'd old tags:          {total('checkpoint_gc')}")
    lines.append(f"  overflow-skipped steps: {total('overflow_skip')}")
    lines.append(f"  loss spikes:            {total('loss_spike')}")
    lines.append(f"  stall warnings:         {total('stall_warning')}")
    lines.append(f"  watchdog violations:    {violations}")
    lines.append(f"  preemption checkpoints: {total('preemption_checkpoint')}")
    # elastic training runtime (Reliability/elastic/* — the closed registry
    # in telemetry/schema.py; docs/reliability.md "Elastic training &
    # universal checkpoint")
    if any(k.startswith("elastic/") for k in counts):
        lines.append("")
        lines.append("  elastic runtime:")
        lines.append(f"    universal saves:      {total('elastic/saves')}")
        lines.append(f"    elastic resumes:      {total('elastic/resumes')}")
        lines.append(f"    topology reshards:    {total('elastic/reshards')}")
        lines.append(f"    host losses detected: "
                     f"{total('elastic/host_loss_detected')}")
        lines.append(f"    drill passes:         "
                     f"{total('elastic/drill_pass')}")
    # numerics-integrity plane (Reliability/integrity/* — the closed
    # registry in telemetry/schema.py; docs/reliability.md "Numerics
    # integrity & SDC")
    if any(k.startswith("integrity/") for k in counts):
        checks = total("integrity/checks")
        mism = total("integrity/mismatches")
        lines.append("")
        lines.append("  numerics integrity:")
        lines.append(f"    fingerprint checks:   {checks}")
        lines.append(f"    shadow audits:        {total('integrity/audit_steps')}")
        lines.append(f"    mismatches:           {mism}"
                     + (f" ({mism / checks:.2%} of checks)" if checks else ""))
        lines.append(f"    host attributions:    "
                     f"{total('integrity/attributed_host')}")
        lines.append(f"    quarantines:          "
                     f"{total('integrity/quarantines')}")
        lines.append(f"    checkpoint walk-backs:"
                     f" {total('integrity/walkbacks')}")
    return "\n".join(lines)


def memory_report(events: List[dict]) -> str:
    """``--memory``: the tiered memory subsystem's ``Memory/tier/*`` stream
    (docs/memory.md) — per-tier resident bytes, transfer volume and the
    measured compute-overlap fraction, prefetch hit/miss, and the serving
    KV host-spill pool occupancy — plus the open ``Memory/{bytes_in_use,
    peak_bytes}`` allocator gauges. Tier series carry gauge/cumulative
    values, so the last sample per series is current."""
    tier = [e for e in events if e["name"].startswith("Memory/tier/")]
    alloc = [e for e in events if e["name"].startswith("Memory/")
             and not e["name"].startswith("Memory/tier/")]
    if not tier and not alloc:
        return "memory: no Memory/* events in this file"
    lines = []

    def last(evs: List[dict], name: str) -> float:
        vals = [e["value"] for e in evs if e["name"] == name]
        return float(vals[-1]) if vals else 0.0

    if tier:
        t = lambda m: last(tier, f"Memory/tier/{m}")  # noqa: E731
        lines.append(f"tiered memory ({len(tier)} Memory/tier/* events)")
        lines.append(f"  host tier resident:   "
                     f"{_fmt_bytes(t('resident_bytes_host'))}")
        lines.append(f"  file tier resident:   "
                     f"{_fmt_bytes(t('resident_bytes_file'))}")
        lines.append(f"  transfers:            "
                     f"{_fmt_bytes(t('transfer_d2h_bytes'))} D2H / "
                     f"{_fmt_bytes(t('transfer_h2d_bytes'))} H2D "
                     f"({t('offloads'):.0f} offloads, "
                     f"{t('restores'):.0f} restores)")
        busy, ov = t("transfer_busy_ms"), t("overlap_ms")
        lines.append(f"  transfer wall time:   {busy:.1f} ms "
                     f"({ov:.1f} ms hidden under compute → "
                     f"overlap_frac {t('overlap_frac'):.2f})")
        hits, misses = t("prefetch_hits"), t("prefetch_misses")
        tot = hits + misses
        lines.append(f"  prefetch:             {hits:.0f} hits / "
                     f"{misses:.0f} misses"
                     + (f" ({hits / tot:.1%} fully hidden)" if tot else ""))
        if any(e["name"].startswith("Memory/tier/kv_") for e in tier):
            lines.append(f"  KV host-spill pool:   "
                         f"{t('kv_spilled_blocks'):.0f} blocks "
                         f"({_fmt_bytes(t('kv_spilled_bytes'))}); "
                         f"{t('kv_spills'):.0f} spills, "
                         f"{t('kv_restores'):.0f} restores")
    if alloc:
        if tier:
            lines.append("")
        lines.append(f"device allocator")
        lines.append(f"  bytes in use:         "
                     f"{_fmt_bytes(last(alloc, 'Memory/bytes_in_use'))}")
        lines.append(f"  peak bytes:           "
                     f"{_fmt_bytes(last(alloc, 'Memory/peak_bytes'))}")
    return "\n".join(lines)


def serving(events: List[dict]) -> str:
    """``--serving``: prefix-cache hit-rate, prefill tokens saved, retained-
    pool occupancy and evictions from the ``Serving/prefix_cache/*`` stream,
    the speculative-decoding efficiency counters from ``Serving/spec/*``,
    the continuous-batching scheduler counters from ``Serving/sched/*``
    (queue depth, admitted/rejected/preempted, queue-wait percentiles,
    goodput-under-SLO), the multi-replica router placement counters from
    ``Serving/router/*``, and the fleet-resilience counters from
    ``Serving/fleet/*`` (failovers, replayed tokens, circuit-breaker
    transitions, shed requests, degradation level — docs/serving.md), and
    the quantized-KV-cache gauges from ``Serving/kv_quant/*`` (resident
    quantized blocks, bytes saved vs bf16, dequant-error bound, fused-
    dequant flag — docs/serving.md "Quantized KV cache"), and the
    disaggregated prefill/decode counters from ``Serving/disagg/*``
    (handoffs, wire bytes vs bf16-equivalent, chain-hash dedup savings —
    docs/serving.md "Disaggregated prefill/decode"). These
    series carry CUMULATIVE counter values (gauges for occupancy/rates), so
    the last sample per series is the run total — unlike
    ``--reliability``'s one-line-per-occurrence."""
    srv = [e for e in events if e["name"].startswith("Serving/prefix_cache/")]
    spec = [e for e in events if e["name"].startswith("Serving/spec/")]
    sched = [e for e in events if e["name"].startswith("Serving/sched/")]
    router = [e for e in events if e["name"].startswith("Serving/router/")]
    fleet = [e for e in events if e["name"].startswith("Serving/fleet/")]
    kvq = [e for e in events if e["name"].startswith("Serving/kv_quant/")]
    disagg = [e for e in events if e["name"].startswith("Serving/disagg/")]
    if not srv and not spec and not sched and not router and not fleet \
            and not kvq and not disagg:
        return ("serving: no Serving/{prefix_cache,spec,sched,router,fleet,"
                "kv_quant,disagg}/* events in this file")
    lines: List[str] = []
    if kvq:
        kq: Dict[str, float] = {}
        for e in kvq:
            kq[e["name"][len("Serving/kv_quant/"):]] = e["value"]  # last wins
        lines.append(f"KV quantization report ({len(kvq)} events)")
        lines.append(f"  quantized blocks (now): "
                     f"{kq.get('blocks_quantized', 0):,.0f}")
        lines.append(f"  bytes saved vs bf16:    "
                     f"{_fmt_bytes(kq.get('bytes_saved', 0))}")
        lines.append(f"  max abs dequant error:  "
                     f"{kq.get('max_abs_err', 0):.6f} (<= scale/2 bound)")
        fused = kq.get("dequant_fused", 0) >= 1.0
        lines.append(f"  dequant fused in-kernel: {'yes' if fused else 'NO'}"
                     + ("" if fused else
                        "  <-- standalone int8 casts LOSE on the MXU "
                        "(QUANT_TPU_LIVE.json)"))
    if srv:
        if lines:
            lines.append("")
        last: Dict[str, float] = {}
        last_step: Dict[str, int] = {}
        for e in srv:
            key = e["name"][len("Serving/prefix_cache/"):]
            last[key] = e["value"]                   # cumulative: last wins
            last_step[key] = max(last_step.get(key, 0), int(e.get("step", 0)))
        lines.append(f"serving prefix-cache report ({len(srv)} events)")
        lines.append(f"  {'counter':<24} {'total':>14} {'last step':>10}")
        for key in sorted(last):
            lines.append(f"  {key:<24} {last[key]:>14,.0f} "
                         f"{last_step[key]:>10}")
        lines.append("")
        lookups = last.get("lookups", 0.0)
        hits = last.get("hits", 0.0)
        lines.append(f"  admissions (lookups):   {lookups:,.0f}")
        lines.append(f"  prefix hits:            {hits:,.0f}")
        lines.append(f"  hit rate:               "
                     f"{hits / lookups * 100 if lookups else 0.0:.1f}%")
        lines.append(f"  hit tokens:             "
                     f"{last.get('hit_tokens', 0):,.0f}")
        lines.append(f"  prefill tokens saved:   "
                     f"{last.get('prefill_tokens_saved', 0):,.0f}")
        lines.append(f"  copy-on-write copies:   "
                     f"{last.get('cow_copies', 0):,.0f}")
        lines.append(f"  evictions:              "
                     f"{last.get('evictions', 0):,.0f}")
        lines.append(f"  retained blocks (now):  "
                     f"{last.get('retained_blocks', 0):,.0f}")
    if spec:
        if lines:
            lines.append("")
        sp: Dict[str, float] = {}
        for e in spec:
            sp[e["name"][len("Serving/spec/"):]] = e["value"]  # last wins
        lines.append(f"speculative decoding report ({len(spec)} events)")
        steps = sp.get("verify_steps", 0.0) + sp.get("decode_steps", 0.0)
        lines.append(f"  model steps:            {steps:,.0f} "
                     f"({sp.get('verify_steps', 0):,.0f} verify, "
                     f"{sp.get('decode_steps', 0):,.0f} plain decode)")
        lines.append(f"  drafted tokens:         "
                     f"{sp.get('drafted_tokens', 0):,.0f}")
        lines.append(f"  accepted tokens:        "
                     f"{sp.get('accepted_tokens', 0):,.0f}")
        lines.append(f"  rolled-back tokens:     "
                     f"{sp.get('rolled_back_tokens', 0):,.0f}")
        lines.append(f"  emitted tokens:         "
                     f"{sp.get('emitted_tokens', 0):,.0f}")
        lines.append(f"  accept rate:            "
                     f"{sp.get('accept_rate', 0) * 100:.1f}%")
        lines.append(f"  mean accepted length:   "
                     f"{sp.get('mean_accepted_len', 0):.2f} tok/verify")
        lines.append(f"  tokens per model step:  "
                     f"{sp.get('tokens_per_step', 0):.2f} per sequence")
        lines.append(f"  verify batch occupancy: "
                     f"{sp.get('verify_batch_occupancy', 0) * 100:.1f}%")
        if sp.get("fused_verify_steps"):
            lines.append(f"  fused verify steps:     "
                         f"{sp.get('fused_verify_steps', 0):,.0f} of "
                         f"{sp.get('verify_steps', 0):,.0f} rode the "
                         f"paged-decode kernel (zero prefill-shaped "
                         f"dispatches)")
    if sched:
        if lines:
            lines.append("")
        sc: Dict[str, float] = {}
        for e in sched:
            sc[e["name"][len("Serving/sched/"):]] = e["value"]  # last wins
        lines.append(f"scheduler report ({len(sched)} events)")
        lines.append(f"  submitted:              {sc.get('submitted', 0):,.0f}"
                     f"  (admitted {sc.get('admitted', 0):,.0f}, chunked "
                     f"{sc.get('chunked_admissions', 0):,.0f}, rejected "
                     f"{sc.get('rejected', 0):,.0f}, expired "
                     f"{sc.get('expired', 0):,.0f})")
        lines.append(f"  preempted / resumed:    "
                     f"{sc.get('preempted', 0):,.0f} / "
                     f"{sc.get('resumed', 0):,.0f}")
        lines.append(f"  completed:              "
                     f"{sc.get('completed', 0):,.0f}  (SLO met "
                     f"{sc.get('slo_met', 0):,.0f}, missed "
                     f"{sc.get('slo_missed', 0):,.0f})")
        lines.append(f"  goodput under SLO:      "
                     f"{sc.get('goodput_frac', 0) * 100:.1f}% of completions"
                     f"  ({sc.get('goodput_rps', 0):.2f} req/s)")
        lines.append(f"  queue depth (now):      "
                     f"{sc.get('queue_depth', 0):,.0f}")
        lines.append(f"  queue wait ms p50/p90/p99: "
                     f"{sc.get('queue_wait_ms_p50', 0):.2f} / "
                     f"{sc.get('queue_wait_ms_p90', 0):.2f} / "
                     f"{sc.get('queue_wait_ms_p99', 0):.2f}"
                     f"  ({sc.get('queue_wait_ms_count', 0):,.0f} samples)")
        lines.append(f"  scheduler ticks:        {sc.get('ticks', 0):,.0f}"
                     f"  ({sc.get('tokens_emitted', 0):,.0f} tokens "
                     f"emitted)")
    if router:
        if lines:
            lines.append("")
        rt: Dict[str, float] = {}
        for e in router:
            rt[e["name"][len("Serving/router/"):]] = e["value"]  # last wins
        lines.append(f"router report ({len(router)} events)")
        reqs = rt.get("requests", 0.0)
        lines.append(f"  requests routed:        {reqs:,.0f} across "
                     f"{rt.get('replicas', 0):,.0f} active replicas")
        aff_pct = rt.get("affinity_hits", 0) / reqs * 100 if reqs else 0.0
        lines.append(f"  prefix-affinity hits:   "
                     f"{rt.get('affinity_hits', 0):,.0f}  "
                     f"({aff_pct:.1f}% of placements)")
        lines.append(f"  session-sticky hits:    "
                     f"{rt.get('session_hits', 0):,.0f}")
        lines.append(f"  load fallbacks:         "
                     f"{rt.get('load_fallbacks', 0):,.0f}")
        lines.append(f"  admission fallbacks:    "
                     f"{rt.get('reject_fallbacks', 0):,.0f}")
        lines.append(f"  drains:                 {rt.get('drains', 0):,.0f}")
    if fleet:
        if lines:
            lines.append("")
        fl: Dict[str, float] = {}
        for e in fleet:
            fl[e["name"][len("Serving/fleet/"):]] = e["value"]  # last wins
        lines.append(f"fleet resilience report ({len(fleet)} events)")
        lines.append(f"  failovers:              "
                     f"{fl.get('failovers', 0):,.0f}  "
                     f"({fl.get('replayed_tokens', 0):,.0f} tokens replayed)")
        lines.append(f"  tick faults:            "
                     f"{fl.get('tick_faults', 0):,.0f}  (slow ticks "
                     f"{fl.get('slow_ticks', 0):,.0f}, probes "
                     f"{fl.get('probe_ticks', 0):,.0f})")
        lines.append(f"  circuit transitions:    "
                     f"{fl.get('circuit_open', 0):,.0f} open / "
                     f"{fl.get('circuit_half_open', 0):,.0f} half-open / "
                     f"{fl.get('circuit_closed', 0):,.0f} closed")
        lines.append(f"  shed requests:          "
                     f"{fl.get('shed_requests', 0):,.0f}")
        lines.append(f"  degrade level (now):    "
                     f"{fl.get('degrade_level', 0):,.0f}  "
                     f"({fl.get('degrade_shifts', 0):,.0f} shifts)")
        lines.append(f"  broken replicas (now):  "
                     f"{fl.get('broken_replicas', 0):,.0f}")
    if disagg:
        if lines:
            lines.append("")
        dg: Dict[str, float] = {}
        for e in disagg:
            dg[e["name"][len("Serving/disagg/"):]] = e["value"]  # last wins
        lines.append(f"disaggregation report ({len(disagg)} events)")
        lines.append(f"  tiers:                  "
                     f"{dg.get('prefill_replicas', 0):,.0f} prefill / "
                     f"{dg.get('decode_replicas', 0):,.0f} decode")
        lines.append(f"  kv handoffs:            "
                     f"{dg.get('handoffs', 0):,.0f}  "
                     f"({dg.get('blocks_shipped', 0):,.0f} blocks shipped)")
        lines.append(f"  wire bytes:             "
                     f"{_fmt_bytes(dg.get('wire_bytes', 0))} of "
                     f"{_fmt_bytes(dg.get('bf16_equiv_bytes', 0))} "
                     f"bf16-equiv ({dg.get('wire_ratio', 0):.3f}x)")
        lines.append(f"  dedup (chain-hash):     "
                     f"{dg.get('dedup_blocks', 0):,.0f} blocks off the wire "
                     f"({_fmt_bytes(dg.get('dedup_bytes_saved', 0))} saved)")
        lines.append(f"  import drops/failures:  "
                     f"{dg.get('import_dropped', 0):,.0f} / "
                     f"{dg.get('import_failures', 0):,.0f}")
        lines.append(f"  tier fallbacks:         "
                     f"{dg.get('tier_fallbacks', 0):,.0f} admission / "
                     f"{dg.get('handoff_fallbacks', 0):,.0f} handoff")
    return "\n".join(lines)


def latency(events: List[dict]) -> str:
    """``--latency``: request-latency SLO percentiles from the
    ``Serving/latency/*`` stream (TTFT, inter-token latency, queue time,
    e2e — docs/serving.md). These are gauges: the last sample per series is
    the run's value."""
    lat = [e for e in events if e["name"].startswith("Serving/latency/")]
    if not lat:
        return "latency: no Serving/latency/* events in this file"
    last: Dict[str, float] = {}
    for e in lat:
        last[e["name"][len("Serving/latency/"):]] = e["value"]
    metrics = sorted({k.rsplit("_", 1)[0] for k in last})
    lines = [f"serving latency SLOs ({len(lat)} events)"]
    lines.append(f"  {'metric':<12} {'count':>7} {'p50':>10} {'p90':>10} "
                 f"{'p99':>10}")
    for m in metrics:
        lines.append(
            f"  {m:<12} {last.get(m + '_count', 0):>7,.0f} "
            f"{last.get(m + '_p50', 0):>10.2f} "
            f"{last.get(m + '_p90', 0):>10.2f} "
            f"{last.get(m + '_p99', 0):>10.2f}")
    lines.append("")
    lines.append("  (ms; ttft = time to first token, itl = inter-token "
                 "latency, queue = admit→first compute, e2e = admit→finish)")
    return "\n".join(lines)


def trace_report(path: str) -> str:
    """``--trace <out.json>``: summarize a Chrome-trace / Perfetto JSON file
    (a flight-recorder dump): span counts + total/mean duration per name,
    the slowest individual spans, and instant-event counts."""
    with open(path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    spans = [e for e in evs if e.get("ph") == "X"]
    instants = [e for e in evs if e.get("ph") in ("i", "I")]
    meta = doc.get("otherData", {}) if isinstance(doc, dict) else {}
    lines = [f"trace report: {len(spans)} spans, {len(instants)} instants"
             + (f" (dump reason: {meta['reason']})" if meta.get("reason")
                else "")]
    if not spans and not instants:
        return lines[0]
    per: Dict[str, List[float]] = {}
    for e in spans:
        per.setdefault(e.get("name", "?"), []).append(float(e.get("dur", 0)))
    if per:
        lines.append("")
        lines.append(f"  {'span':<28} {'count':>6} {'total ms':>10} "
                     f"{'mean ms':>10} {'max ms':>10}")
        for name, durs in sorted(per.items(),
                                 key=lambda kv: -sum(kv[1])):
            lines.append(f"  {name:<28} {len(durs):>6} "
                         f"{sum(durs) / 1e3:>10.2f} "
                         f"{sum(durs) / len(durs) / 1e3:>10.3f} "
                         f"{max(durs) / 1e3:>10.3f}")
    top = sorted(spans, key=lambda e: -float(e.get("dur", 0)))[:5]
    if top:
        lines.append("")
        lines.append("  slowest spans:")
        for e in top:
            args = e.get("args", {})
            extras = ", ".join(f"{k}={v}" for k, v in args.items()
                               if k not in ("trace_id", "span_id",
                                            "parent_id"))
            lines.append(f"    {e.get('name', '?'):<24} "
                         f"{float(e.get('dur', 0)) / 1e3:>9.3f} ms"
                         + (f"  ({extras})" if extras else ""))
    if instants:
        per_i: Dict[str, int] = {}
        for e in instants:
            per_i[e.get("name", "?")] = per_i.get(e.get("name", "?"), 0) + 1
        lines.append("")
        lines.append("  instants: " + ", ".join(
            f"{n}×{c}" for n, c in sorted(per_i.items())))
    return "\n".join(lines)


def summarize(events: List[dict], last: int = 0) -> str:
    if last > 0:
        steps = sorted({e.get("step", 0) for e in events})[-last:]
        events = [e for e in events if e.get("step", 0) in set(steps)]
    by_name = _series(events)
    lines: List[str] = []
    n_steps = len({e.get("step", 0) for e in events})
    lines.append(f"telemetry report: {len(events)} events over "
                 f"{n_steps} steps")

    phase = {n: s for n, s in by_name.items()
             if n.startswith("Train/Step/") and n.endswith("_ms")}
    if phase:
        lines.append("")
        lines.append("step time (ms)")
        lines.append(f"  {'phase':<16} {'count':>6} {'mean':>10} "
                     f"{'min':>10} {'max':>10} {'last':>10}")
        for name, recs in phase.items():
            vals = [r["value"] for r in recs]
            label = name[len("Train/Step/"):-len("_ms")]
            lines.append(f"  {label:<16} {len(vals):>6} "
                         f"{sum(vals) / len(vals):>10.2f} {min(vals):>10.2f} "
                         f"{max(vals):>10.2f} {vals[-1]:>10.2f}")

    comm: Dict[str, Dict[str, float]] = {}
    for name, recs in by_name.items():
        if not name.startswith("Comm/"):
            continue
        _, op, kind = name.split("/", 2)
        # per-trace cumulative counters: the last sample is the total
        comm.setdefault(op, {})[kind] = recs[-1]["value"]
    if comm:
        lines.append("")
        lines.append("comm volume (per compiled step)")
        lines.append(f"  {'op':<24} {'count':>6} {'bytes':>14}")
        for op, kinds in sorted(comm.items()):
            lines.append(f"  {op:<24} {int(kinds.get('count', 0)):>6} "
                         f"{_fmt_bytes(kinds.get('bytes', 0.0)):>14}")

    mem = {n: s for n, s in by_name.items() if n.startswith("Memory/")}
    if mem:
        lines.append("")
        lines.append("device memory")
        for name, recs in sorted(mem.items()):
            vals = [r["value"] for r in recs]
            lines.append(f"  {name[len('Memory/'):]:<16} "
                         f"last {_fmt_bytes(vals[-1]):>14}   "
                         f"max {_fmt_bytes(max(vals)):>14}")

    other = {n: s for n, s in by_name.items()
             if n not in phase and n not in mem
             and not n.startswith("Comm/")}
    if other:
        lines.append("")
        lines.append("scalars (last value)")
        for name, recs in other.items():
            lines.append(f"  {name:<32} {recs[-1]['value']:.6g}")
    return "\n".join(lines)


def fleet(events: List[dict]) -> str:
    """``--fleet``: the fleet observability plane's offline view — the
    cross-replica ``Fleet/*`` rollup, the per-tenant SLO table
    (``Serving/tenant/*``), and the burn-rate alert history — rendered from
    one or more (merged, provenance-tagged) per-replica JSONL files."""
    by_name = _series(events)
    have = any(n.startswith(("Fleet/", "Serving/tenant/")) for n in by_name)
    if not have:
        return ("fleet: no Fleet/* or Serving/tenant/* events in this file\n"
                "  (enable the serving.obs block and publish via "
                "router.publish_fleet_obs_telemetry)")
    lines = ["fleet observability"]
    sources = sorted({e["source"] for e in events if "source" in e})
    if sources:
        lines.append(f"  merged from {len(sources)} file(s): "
                     + ", ".join(sources))

    # -- per-replica rollup (last sample per series wins) ---------------- #
    replicas: Dict[str, Dict[str, float]] = {}
    for name, recs in by_name.items():
        parts = name.split("/")
        if name.startswith("Fleet/replica") and len(parts) == 3:
            replicas.setdefault(parts[1][len("replica"):],
                                {})[parts[2]] = recs[-1]["value"]
    if replicas:
        cols = ("live", "queue_depth", "completed", "goodput_frac",
                "ttft_ms_p99", "e2e_ms_p99")
        lines.append("")
        lines.append("  per-replica rollup (last sample)")
        lines.append("  " + f"{'replica':<9}"
                     + "".join(f"{c:>14}" for c in cols))
        for r in sorted(replicas, key=lambda x: (len(x), x)):
            row = replicas[r]
            lines.append("  " + f"{r:<9}" + "".join(
                f"{row.get(c, 0.0):>14.3f}" for c in cols))
    agg = {n[len("Fleet/agg/"):]: recs[-1]["value"]
           for n, recs in by_name.items() if n.startswith("Fleet/agg/")}
    if agg:
        lines.append("")
        lines.append("  fleet aggregates (last sample)")
        for key in ("completed_sum", "tokens_emitted_sum",
                    "goodput_frac_mean", "goodput_frac_min",
                    "queue_wait_ms_p99_merged", "ttft_ms_p99_merged",
                    "itl_ms_p99_merged", "e2e_ms_p99_merged"):
            if key in agg:
                lines.append(f"    {key:<28} {agg[key]:,.3f}")
    outlier = {n[len("Fleet/outlier/"):]: recs[-1]["value"]
               for n, recs in by_name.items()
               if n.startswith("Fleet/outlier/")}
    if outlier:
        worst = max(outlier.items(), key=lambda kv: kv[1])
        lines.append(f"    worst replica-outlier delta: {worst[0]} "
                     f"+{worst[1] * 100:.1f}% over the median replica")

    # -- per-tenant SLO table -------------------------------------------- #
    tenants: Dict[str, Dict[str, float]] = {}
    for name, recs in by_name.items():
        parts = name.split("/")
        if name.startswith("Serving/tenant/") and len(parts) == 4:
            tenants.setdefault(parts[2], {})[parts[3]] = recs[-1]["value"]
    if tenants:
        lines.append("")
        lines.append("  per-tenant SLO accounting (last sample)")
        lines.append(f"  {'tenant':<16} {'completed':>10} {'rejected':>9} "
                     f"{'goodput':>9} {'ttft p99':>10} {'burn rate':>10} "
                     f"{'alerts':>7}")
        for t in sorted(tenants):
            row = tenants[t]
            lines.append(
                f"  {t:<16} {row.get('completed', 0.0):>10.0f} "
                f"{row.get('rejected', 0.0):>9.0f} "
                f"{row.get('goodput_frac', 0.0):>9.3f} "
                f"{row.get('ttft_p99_ms', 0.0):>8.1f}ms "
                f"{row.get('slo_burn_rate', 0.0):>10.2f} "
                f"{row.get('slo_burn_alerts', 0.0):>7.0f}")

    # -- burn-rate alert history ----------------------------------------- #
    # the alert counter is cumulative per tenant: every step where it rose
    # is one alert firing (multiwindow burn — fast AND slow window hot)
    fired: List[str] = []
    for name, recs in sorted(by_name.items()):
        parts = name.split("/")
        if not (name.startswith("Serving/tenant/")
                and name.endswith("/slo_burn_alerts")):
            continue
        prev = 0.0
        for r in recs:
            if r["value"] > prev:
                src = f" [{r['source']}]" if "source" in r else ""
                fired.append(f"    step {r.get('step', 0):>6}  "
                             f"tenant {parts[2]}  alert "
                             f"#{int(r['value'])}{src}")
            prev = max(prev, r["value"])
    lines.append("")
    if fired:
        lines.append(f"  burn-rate alert history ({len(fired)} firing(s))")
        lines.extend(fired)
    else:
        lines.append("  burn-rate alert history: none fired")
    return "\n".join(lines)


def tuning(events: List[dict]) -> str:
    """``--tuning``: the self-tuning runtime's offline view — fleet totals
    (trials/accepts/reverts/vetoes/retunes), the per-knob state table, and
    the accepted-winner history, rendered from ``Tune/*`` events emitted by
    ``deepspeed_tpu/tuning`` (docs/tuning.md)."""
    by_name = _series(events)
    if not any(n.startswith("Tune/") for n in by_name):
        return ("tuning: no Tune/* events in this file\n"
                "  (enable the `tuning` config block — training — or the "
                "serving router's `tuning` block)")
    lines = ["self-tuning runtime"]

    totals = {n[len("Tune/total/"):]: recs[-1]["value"]
              for n, recs in by_name.items() if n.startswith("Tune/total/")}
    if totals:
        lines.append("  totals: " + "  ".join(
            f"{k}={int(totals[k])}"
            for k in ("trials", "accepts", "reverts", "vetoes", "retunes",
                      "open_knobs", "closed_knobs") if k in totals))

    # -- per-knob state table (last sample per metric wins) -------------- #
    knobs: Dict[str, Dict[str, float]] = {}
    for name, recs in by_name.items():
        parts = name.split("/")
        if name.startswith("Tune/knob/") and len(parts) == 4:
            knobs.setdefault(parts[2], {})[parts[3]] = recs[-1]["value"]
    if knobs:
        lines.append("")
        lines.append("  per-knob state (value = choice index; Δ = score "
                     "best-vs-baseline, sign per the knob's objective)")
        lines.append(f"  {'knob':<28} {'state':>7} {'value':>6} "
                     f"{'trials':>7} {'accepts':>8} {'reverts':>8} "
                     f"{'vetoes':>7} {'retunes':>8} {'Δ score':>10}")
        for k in sorted(knobs):
            row = knobs[k]
            state = "open" if row.get("active", 0.0) else "closed"
            delta = row.get("score_delta")
            lines.append(
                f"  {k:<28} {state:>7} {row.get('value', 0.0):>6.0f} "
                f"{row.get('trials', 0.0):>7.0f} "
                f"{row.get('accepts', 0.0):>8.0f} "
                f"{row.get('reverts', 0.0):>8.0f} "
                f"{row.get('vetoes', 0.0):>7.0f} "
                f"{row.get('retunes', 0.0):>8.0f} "
                + (f"{delta:>10.4f}" if delta is not None else f"{'-':>10}"))

    # -- accepted-winner history ----------------------------------------- #
    # per-knob accept counters are cumulative: each rise is one accepted arm
    accepted: List[str] = []
    for name, recs in sorted(by_name.items()):
        parts = name.split("/")
        if not (name.startswith("Tune/knob/")
                and name.endswith("/accepts") and len(parts) == 4):
            continue
        prev = 0.0
        for r in recs:
            if r["value"] > prev:
                src = f" [{r['source']}]" if "source" in r else ""
                accepted.append(f"    step {r.get('step', 0):>6}  "
                                f"{parts[2]}  accept "
                                f"#{int(r['value'])}{src}")
            prev = max(prev, r["value"])
    lines.append("")
    if accepted:
        lines.append(f"  accepted winners ({len(accepted)})")
        lines.extend(accepted)
    else:
        lines.append("  accepted winners: none yet")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="*",
                    help="path(s) to events.jsonl telemetry file(s) — "
                         "multiple files (a fleet's per-replica monitors) "
                         "are merged with provenance tags (optional with "
                         "--trace)")
    ap.add_argument("--last", type=int, default=0,
                    help="restrict to the last N steps")
    ap.add_argument("--comm-efficiency", action="store_true",
                    help="print collective count / total algorithmic bytes / "
                         "bytes-per-step (comm-volume regression check)")
    ap.add_argument("--reliability", action="store_true",
                    help="summarize Reliability/* events: skipped steps, "
                         "watchdog trips, checkpoint save/restore/rollback "
                         "counts")
    ap.add_argument("--serving", action="store_true",
                    help="summarize Serving/prefix_cache/* counters "
                         "(hit-rate, prefill tokens saved, retained-pool "
                         "occupancy, evictions), Serving/spec/* "
                         "speculative-decoding counters (accept rate, mean "
                         "accepted length, tokens per model step, verify "
                         "batch occupancy), Serving/sched/* scheduler "
                         "counters (queue depth, admitted/rejected/"
                         "preempted, queue-wait percentiles, goodput-under-"
                         "SLO), Serving/router/* placement counters, and "
                         "Serving/fleet/* resilience counters (failovers, "
                         "circuit-breaker transitions, shed requests, "
                         "degradation level)")
    ap.add_argument("--latency", action="store_true",
                    help="summarize Serving/latency/* SLO percentiles: "
                         "TTFT / inter-token / queue / e2e p50-p90-p99")
    ap.add_argument("--memory", action="store_true",
                    help="summarize the tiered memory subsystem's "
                         "Memory/tier/* stream (per-tier resident bytes, "
                         "transfer volume, measured compute-overlap "
                         "fraction, prefetch hit/miss, KV host-spill pool) "
                         "plus the Memory/* allocator gauges")
    ap.add_argument("--compile", action="store_true", dest="compile_",
                    help="summarize Compile/* recompilation-sentinel "
                         "counters (compiles, cache hits, recompiles, "
                         "compile wall time) and the per-program MFU "
                         "attribution from Train/mfu/* + Serving/mfu/*")
    ap.add_argument("--anomalies", action="store_true",
                    help="summarize recorded Anomaly/* findings (spikes, "
                         "drift, stragglers) and replay the rolling-median/"
                         "MAD detector offline over the Train/Step/*_ms "
                         "series")
    ap.add_argument("--fleet", action="store_true",
                    help="summarize the fleet observability plane: "
                         "cross-replica Fleet/* rollups (per-replica rows, "
                         "aggregates, outlier deltas), the per-tenant SLO "
                         "table (Serving/tenant/* goodput, TTFT p99, burn "
                         "rate), and the burn-rate alert history — pass "
                         "several per-replica events.jsonl paths to merge "
                         "them with provenance tags")
    ap.add_argument("--tuning", action="store_true",
                    help="summarize the self-tuning runtime: Tune/total/* "
                         "fleet counters, the per-knob state table "
                         "(trials/accepts/reverts/vetoes/retunes, applied "
                         "choice, score delta), and the accepted-winner "
                         "history")
    ap.add_argument("--trace", metavar="TRACE_JSON",
                    help="summarize a Chrome-trace/Perfetto JSON flight-"
                         "recorder dump (span durations, slowest spans)")
    ap.add_argument("--all", action="store_true",
                    help="run every section (summary, comm efficiency, "
                         "reliability, serving, latency, compile, "
                         "anomalies, fleet, tuning) in one pass")
    args = ap.parse_args(argv)
    if args.trace:
        try:
            print(trace_report(args.trace))
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if not args.path:
            return 0
        print()
    if not args.path:
        ap.error("path to an events.jsonl file is required "
                 "(or use --trace <out.json>)")
    try:
        events = load_events(*args.path)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not events:
        print(f"error: no telemetry events in {', '.join(args.path)}",
              file=sys.stderr)
        return 1
    if args.all:
        sections = [summarize(events, last=args.last), comm_efficiency(events),
                    reliability(events), serving(events), latency(events),
                    memory_report(events), compile_report(events),
                    anomalies(events), fleet(events), tuning(events)]
        print("\n\n".join(sections))
        return 0
    if args.compile_:
        print(compile_report(events))
        return 0
    if args.anomalies:
        print(anomalies(events))
        return 0
    if args.comm_efficiency:
        print(comm_efficiency(events))
        return 0
    if args.reliability:
        print(reliability(events))
        return 0
    if args.serving:
        print(serving(events))
        return 0
    if args.latency:
        print(latency(events))
        return 0
    if args.memory:
        print(memory_report(events))
        return 0
    if args.fleet:
        print(fleet(events))
        return 0
    if args.tuning:
        print(tuning(events))
        return 0
    print(summarize(events, last=args.last))
    return 0


if __name__ == "__main__":
    sys.exit(main())
