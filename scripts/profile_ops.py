#!/usr/bin/env python
"""Amortized op microbenchmarks: chain N iterations inside one jit so the
per-dispatch tunnel overhead doesn't pollute the numbers."""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

PEAK = 197e12
REPS = 20


def chain_bench(op, args, flops, steps=5, warmup=2):
    """op(*args) -> out; runs REPS data-dependent iterations inside one jit."""

    def chained(*args):
        def body(carry, _):
            out = op(*args[:-1], carry)
            # fold output back into the last arg slot (same shape assumed)
            return out, ()

        out, _ = lax.scan(body, args[-1], None, length=REPS)
        return out

    f = jax.jit(chained)
    for _ in range(warmup):
        out = f(*args)
    float(jnp.sum(out.astype(jnp.float32)))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = f(*args)
    float(jnp.sum(out.astype(jnp.float32)))
    dt = (time.perf_counter() - t0) / (steps * REPS)
    return dt, flops / dt / PEAK


def main():
    key = jax.random.PRNGKey(0)
    batch, seqlen, hidden = 8, 2048, 1024
    heads = hidden // 64
    M = batch * seqlen

    # matmul ceiling: out shape must match chained arg; use square-ish
    for K, N in [(1024, 1024), (2048, 2048), (4096, 4096), (8192, 8192)]:
        a = jax.random.normal(key, (M, K), jnp.bfloat16)
        b = jax.random.normal(key, (K, N), jnp.bfloat16)
        # chain on `a` only if N == K
        if N == K:
            dt, mfu = chain_bench(lambda b, a: (a @ b)[:, :K], (b, a), 2 * M * K * N)
            print(f"matmul [{M}x{K}]@[{K}x{N}]: {dt*1e3:7.3f} ms  mfu={mfu:.3f}")

    # attention: chain on q (same shape as out)
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    for s in (1024, 2048, 4096):
        q = jax.random.normal(key, (batch, s, heads, 64), jnp.bfloat16)
        kv = jax.random.normal(key, (batch, s, heads // 2, 64), jnp.bfloat16)
        attn_flops = 4 * batch * s * s * heads * 64 / 2

        def attn_op(k, v, q):
            return flash_attention(q, k, v, causal=True).reshape(q.shape)

        dt, mfu = chain_bench(attn_op, (kv, kv, q), attn_flops)
        print(f"flash[s={s}]: {dt*1e3:7.3f} ms  mfu={mfu:.3f}")

    # xla attention reference
    def xla_attn(k, v, q):
        b, s, nh, hd = q.shape
        nkv = k.shape[2]
        qr = q.reshape(b, s, nkv, nh // nkv, hd)
        logits = jnp.einsum("bskgh,btkh->bkgst", qr, k) / (hd ** 0.5)
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask[None, None, None], logits.astype(jnp.float32), -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        out = jnp.einsum("bkgst,btkh->bskgh", probs, v)
        return out.reshape(q.shape)

    s = 2048
    q = jax.random.normal(key, (batch, s, heads, 64), jnp.bfloat16)
    kv = jax.random.normal(key, (batch, s, heads // 2, 64), jnp.bfloat16)
    attn_flops = 4 * batch * s * s * heads * 64 / 2
    dt, mfu = chain_bench(xla_attn, (kv, kv, q), attn_flops)
    print(f"xla_attn[s={s}]: {dt*1e3:7.3f} ms  mfu={mfu:.3f}")


if __name__ == "__main__":
    main()
