#!/usr/bin/env python
"""Long-context proof on one chip (VERDICT r3 item 8).

Runs FPDT attention with KV host-offload double buffering at escalating
sequence lengths (128K -> 1M tokens) on the real chip, fwd+bwd, and records
(seq, step time, attention MFU, peak HBM) per row — the single-chip analog of
BASELINE.md's Ulysses/FPDT long-context rows (reference proof point:
blogs/ulysses-offload 2M tokens on 4xA100 via chunked KV streaming).

Prints ONE JSON line. Safe to run on CPU (tiny shapes, smoke only).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _probe_common import finalize, install_term_handler  # noqa: E402

# stdout carries exactly ONE JSON line; package logs go to stderr

RESULT = {"metric": "fpdt_longctx_max_seq", "value": 0, "unit": "tokens",
          "vs_baseline": 0.0, "detail": {}}


def peak_hbm_bytes(dev):
    try:
        stats = dev.memory_stats()
        return int(stats.get("peak_bytes_in_use", 0))
    except Exception:
        return 0


def main():
    install_term_handler(RESULT)
    import jax

    if os.environ.get("DSTPU_BENCH_FORCE_CPU"):
        # the axon sitecustomize forces jax_platforms=axon,cpu programmatically;
        # only the in-process config update bypasses a wedged tunnel
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    try:  # persistent XLA cache: re-runs across tunnel windows skip compiles
        jax.config.update("jax_compilation_cache_dir", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".xla_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass

    from deepspeed_tpu.sequence.fpdt import fpdt_attention

    backend = jax.default_backend()
    RESULT["detail"]["backend"] = backend
    dev = jax.devices()[0]
    on_tpu = backend == "tpu"
    # [B=1, S, H, D] bf16; GQA-narrow KV (4 kv heads) like the bench model
    H, Hkv, D = 8, 4, 128
    if on_tpu:
        seqs = [128 * 1024, 256 * 1024, 512 * 1024, 1024 * 1024]
        chunk_tokens = 8192
    else:
        seqs = [4096]
        chunk_tokens = 1024
    budget_s = float(os.environ.get("DSTPU_LONGCTX_BUDGET_S", 1800))
    t_start = time.perf_counter()

    def loss_fn(q, k, v, chunks):
        o = fpdt_attention(q, k, v, chunks=chunks, causal=True,
                           offload_kv=on_tpu)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    rows = {}
    RESULT["detail"]["rows"] = rows
    best = 0
    for S in seqs:
        if time.perf_counter() - t_start > budget_s:
            rows[str(S)] = "skipped: budget exhausted"
            continue
        chunks = max(2, S // chunk_tokens)
        try:
            key = jax.random.PRNGKey(0)
            kq, kk, kv_ = jax.random.split(key, 3)
            q = jax.random.normal(kq, (1, S, H, D), jnp.bfloat16)
            k = jax.random.normal(kk, (1, S, Hkv, D), jnp.bfloat16)
            v = jax.random.normal(kv_, (1, S, Hkv, D), jnp.bfloat16)
            grad = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)),
                           static_argnums=(3,))
            out = grad(q, k, v, chunks)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
            float(jnp.sum(out[0].astype(jnp.float32)))  # tunnel-safe sync
            t0 = time.perf_counter()
            out = grad(q, k, v, chunks)
            float(jnp.sum(out[0].astype(jnp.float32)))
            dt = time.perf_counter() - t0
            # causal attention fwd flops = 2 matmuls * 2*B*H*(S^2/2)*D;
            # bwd ~= 2x fwd (recompute excluded from the 6N-style account)
            flops = 3 * (2 * H * (S ** 2) * D)
            from bench import peak_flops_per_chip

            peak = peak_flops_per_chip(jax)
            rows[str(S)] = {
                "step_s": round(dt, 3),
                "attn_mfu": round(flops / dt / peak, 4),
                "peak_hbm_gb": round(peak_hbm_bytes(dev) / 2**30, 2),
                "chunks": chunks,
            }
            best = S
            sys.stderr.write(f"[longctx] S={S}: {rows[str(S)]}\n")
        except Exception as e:
            rows[str(S)] = f"error: {str(e)[-200:]}"
            sys.stderr.write(f"[longctx] S={S} failed: {str(e)[-300:]}\n")
            break  # OOM at S means 2S would also fail
    RESULT["value"] = best
    # baseline: reference FPDT reaches 2M tokens on 4 GPUs => 512K/device
    RESULT["vs_baseline"] = round(best / (512 * 1024), 4)
    # explicit ok: hitting the OOM frontier after ≥1 passing size IS a
    # successful run (value = max proven S); only an immediate first-row
    # failure (best == 0) means the probe found nothing
    finalize(RESULT, ok=best > 0)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # always emit the JSON line
        RESULT["detail"]["error"] = str(e)[-2000:]
        finalize(RESULT, ok=False)
