// Async host<->disk I/O engine — DeepNVMe equivalent.
//
// TPU-native counterpart of the reference's csrc/aio tier
// (deepspeed_aio_thread.cpp thread pool, py_ds_aio.cpp:22 `aio_handle`
// pybind with read/write/pread/pwrite async+wait): a pthread worker pool
// servicing a queue of chunked pread/pwrite requests against O_DIRECT-less
// file descriptors. The reference builds on libaio/io_uring + pinned CUDA
// buffers; on a TPU host the transfer overlap that matters is
// disk <-> host RAM (the TPU DMA is driven separately by jax device_put),
// so a portable thread pool with positional I/O covers the same capability
// without kernel-API dependencies. Large requests are split into
// `block_size` chunks so multiple workers stream one tensor concurrently.
//
// Plain C ABI for ctypes.

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Chunk {
  int op;  // 0 = read, 1 = write
  std::string path;
  char* buf;
  int64_t nbytes;
  int64_t offset;
};

struct Handle {
  int64_t block_size;
  int n_threads;
  std::vector<std::thread> workers;
  std::deque<Chunk> queue;
  std::mutex mu;
  std::condition_variable cv;       // work available
  std::condition_variable done_cv;  // all drained
  int64_t inflight = 0;
  int64_t errors = 0;
  bool stop = false;

  void worker() {
    for (;;) {
      Chunk c;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        c = std::move(queue.front());
        queue.pop_front();
      }
      bool ok = run(c);
      {
        std::lock_guard<std::mutex> lk(mu);
        if (!ok) ++errors;
        if (--inflight == 0) done_cv.notify_all();
      }
    }
  }

  static bool run(const Chunk& c) {
    int flags = (c.op == 0) ? O_RDONLY : (O_WRONLY | O_CREAT);
    int fd = ::open(c.path.c_str(), flags, 0644);
    if (fd < 0) return false;
    int64_t done = 0;
    bool ok = true;
    while (done < c.nbytes) {
      ssize_t r = (c.op == 0)
                      ? ::pread(fd, c.buf + done, c.nbytes - done,
                                c.offset + done)
                      : ::pwrite(fd, c.buf + done, c.nbytes - done,
                                 c.offset + done);
      if (r <= 0) {
        ok = false;
        break;
      }
      done += r;
    }
    ::close(fd);
    return ok;
  }

  void submit(int op, const char* path, void* buf, int64_t nbytes,
              int64_t offset) {
    std::lock_guard<std::mutex> lk(mu);
    for (int64_t off = 0; off < nbytes; off += block_size) {
      int64_t len = std::min(block_size, nbytes - off);
      queue.push_back(Chunk{op, path, (char*)buf + off, len, offset + off});
      ++inflight;
    }
    cv.notify_all();
  }

  int64_t wait() {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [&] { return inflight == 0; });
    int64_t e = errors;
    errors = 0;
    return e;
  }
};

}  // namespace

extern "C" {

void* ds_aio_create(int64_t block_size, int n_threads) {
  Handle* h = new Handle();
  h->block_size = block_size > 0 ? block_size : (1 << 20);
  h->n_threads = n_threads > 0 ? n_threads : 1;
  for (int i = 0; i < h->n_threads; ++i)
    h->workers.emplace_back([h] { h->worker(); });
  return h;
}

void ds_aio_destroy(void* hp) {
  Handle* h = (Handle*)hp;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->stop = true;
  }
  h->cv.notify_all();
  for (auto& t : h->workers) t.join();
  delete h;
}

// async positional read/write; call ds_aio_wait to drain.
void ds_aio_pread(void* hp, const char* path, void* buf, int64_t nbytes,
                  int64_t offset) {
  ((Handle*)hp)->submit(0, path, buf, nbytes, offset);
}

void ds_aio_pwrite(void* hp, const char* path, void* buf, int64_t nbytes,
                   int64_t offset) {
  ((Handle*)hp)->submit(1, path, buf, nbytes, offset);
}

// returns the number of failed chunks since the previous wait (0 = success).
int64_t ds_aio_wait(void* hp) { return ((Handle*)hp)->wait(); }

// blocking whole-file helpers (reference aio_handle.read/write).
int64_t ds_aio_read_sync(void* hp, const char* path, void* buf,
                         int64_t nbytes) {
  Handle* h = (Handle*)hp;
  h->submit(0, path, buf, nbytes, 0);
  return h->wait();
}

int64_t ds_aio_write_sync(void* hp, const char* path, void* buf,
                          int64_t nbytes) {
  Handle* h = (Handle*)hp;
  h->submit(1, path, buf, nbytes, 0);
  return h->wait();
}

int64_t ds_aio_file_size(const char* path) {
  struct stat st;
  if (::stat(path, &st) != 0) return -1;
  return (int64_t)st.st_size;
}

}  // extern "C"
