// Async host<->disk I/O engine — DeepNVMe equivalent.
//
// TPU-native counterpart of the reference's csrc/aio tier
// (deepspeed_aio_thread.cpp thread pool + io_uring path, py_ds_aio.cpp:22
// `aio_handle` pybind with read/write/pread/pwrite async+wait). Two engines:
//
// 1. io_uring (preferred, raw syscalls — no liburing dependency): one
//    submitter thread batches chunked READ/WRITE SQEs into a kernel ring,
//    so N in-flight ops cost ~1 syscall per batch instead of one blocking
//    pread per chunk-thread. Short reads/writes are resubmitted.
// 2. pthread worker pool with positional I/O (fallback when io_uring_setup
//    is unavailable — seccomp'd containers, old kernels).
//
// Large requests are split into `block_size` chunks so one tensor streams
// through multiple ring slots / workers concurrently.
//
// Plain C ABI for ctypes.

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <unistd.h>

#if defined(__has_include)
#if __has_include(<linux/io_uring.h>)
#include <linux/io_uring.h>
#define DS_HAVE_IO_URING 1
#endif
#endif

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- io_uring
#ifdef DS_HAVE_IO_URING
static int sys_io_uring_setup(unsigned entries, struct io_uring_params* p) {
  return (int)syscall(__NR_io_uring_setup, entries, p);
}
static int sys_io_uring_enter(int fd, unsigned to_submit,
                              unsigned min_complete, unsigned flags) {
  return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete, flags,
                      nullptr, 0);
}

struct Ring {
  int ring_fd = -1;
  unsigned entries = 0;
  // SQ
  void* sq_ptr = nullptr;
  size_t sq_len = 0;
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned* sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  struct io_uring_sqe* sqes = nullptr;
  size_t sqes_len = 0;
  // CQ
  void* cq_ptr = nullptr;
  size_t cq_len = 0;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned* cq_mask = nullptr;
  struct io_uring_cqe* cqes = nullptr;

  bool init(unsigned want) {
    struct io_uring_params p;
    memset(&p, 0, sizeof(p));
    ring_fd = sys_io_uring_setup(want, &p);
    if (ring_fd < 0) return false;
    entries = p.sq_entries;
    sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_len = p.cq_off.cqes + p.cq_entries * sizeof(struct io_uring_cqe);
    bool single = p.features & IORING_FEAT_SINGLE_MMAP;
    if (single && cq_len > sq_len) sq_len = cq_len;
    sq_ptr = mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                  MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQ_RING);
    if (sq_ptr == MAP_FAILED) return fail();
    if (single) {
      cq_ptr = sq_ptr;
    } else {
      cq_ptr = mmap(nullptr, cq_len, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_CQ_RING);
      if (cq_ptr == MAP_FAILED) return fail();
    }
    sqes_len = p.sq_entries * sizeof(struct io_uring_sqe);
    sqes = (struct io_uring_sqe*)mmap(nullptr, sqes_len,
                                      PROT_READ | PROT_WRITE,
                                      MAP_SHARED | MAP_POPULATE, ring_fd,
                                      IORING_OFF_SQES);
    if (sqes == MAP_FAILED) return fail();
    char* sq = (char*)sq_ptr;
    sq_head = (unsigned*)(sq + p.sq_off.head);
    sq_tail = (unsigned*)(sq + p.sq_off.tail);
    sq_mask = (unsigned*)(sq + p.sq_off.ring_mask);
    sq_array = (unsigned*)(sq + p.sq_off.array);
    char* cq = (char*)cq_ptr;
    cq_head = (unsigned*)(cq + p.cq_off.head);
    cq_tail = (unsigned*)(cq + p.cq_off.tail);
    cq_mask = (unsigned*)(cq + p.cq_off.ring_mask);
    cqes = (struct io_uring_cqe*)(cq + p.cq_off.cqes);
    return true;
  }

  bool fail() {
    close_all();
    return false;
  }

  void close_all() {
    if (sqes && sqes != MAP_FAILED) munmap(sqes, sqes_len);
    if (cq_ptr && cq_ptr != sq_ptr && cq_ptr != MAP_FAILED)
      munmap(cq_ptr, cq_len);
    if (sq_ptr && sq_ptr != MAP_FAILED) munmap(sq_ptr, sq_len);
    if (ring_fd >= 0) ::close(ring_fd);
    ring_fd = -1;
    sq_ptr = cq_ptr = nullptr;
    sqes = nullptr;
  }

  unsigned sq_space() const {
    unsigned head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
    return entries - (*sq_tail - head);
  }

  void push_sqe(int op, int fd, void* buf, unsigned len, int64_t off,
                uint64_t user_data) {
    unsigned tail = *sq_tail;
    unsigned idx = tail & *sq_mask;
    struct io_uring_sqe* e = &sqes[idx];
    memset(e, 0, sizeof(*e));
    e->opcode = (op == 0) ? IORING_OP_READ : IORING_OP_WRITE;
    e->fd = fd;
    e->addr = (uint64_t)buf;
    e->len = len;
    e->off = (uint64_t)off;
    e->user_data = user_data;
    sq_array[idx] = idx;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
  }
};
#endif  // DS_HAVE_IO_URING

struct Chunk {
  int op;  // 0 = read, 1 = write
  std::string path;
  char* buf;
  int64_t nbytes;
  int64_t offset;
};

struct Handle {
  int64_t block_size;
  int n_threads;
  std::vector<std::thread> workers;
  std::deque<Chunk> queue;
  std::mutex mu;
  std::condition_variable cv;       // work available
  std::condition_variable done_cv;  // all drained
  int64_t inflight = 0;
  int64_t errors = 0;
  bool stop = false;

  bool use_uring = false;

  void finish_chunk(bool ok) {
    std::lock_guard<std::mutex> lk(mu);
    if (!ok) ++errors;
    if (--inflight == 0) done_cv.notify_all();
  }

#ifdef DS_HAVE_IO_URING
  // io_uring engine state (submitter thread only, except counters under mu)
  Ring ring;
  struct FdEntry {
    int fd;
    int mode;            // 0 = read-only, 1 = read-write
    int64_t in_kernel;   // SQEs referencing this fd (no eviction while > 0)
  };
  std::unordered_map<std::string, FdEntry> fd_cache;
  std::unordered_map<uint64_t, Chunk> pending;
  std::unordered_map<uint64_t, std::string> pending_path;
  uint64_t next_token = 1;
  int64_t kernel_inflight = 0;  // SQEs submitted, CQE not yet reaped
  size_t fd_cache_cap = 256;

  int get_fd(const std::string& path, bool write) {
    auto it = fd_cache.find(path);
    if (it != fd_cache.end() && (!write || it->second.mode == 1))
      return it->second.fd;
    if (it != fd_cache.end()) {  // cached read-only, now need write
      if (it->second.in_kernel == 0) {
        ::close(it->second.fd);
        fd_cache.erase(it);
      } else {
        return -2;  // caller requeues; reopen once in-flight reads drain
      }
    }
    if (fd_cache.size() >= fd_cache_cap) {  // evict an idle entry
      for (auto e = fd_cache.begin(); e != fd_cache.end(); ++e) {
        if (e->second.in_kernel == 0) {
          ::close(e->second.fd);
          fd_cache.erase(e);
          break;
        }
      }
    }
    int flags = write ? (O_RDWR | O_CREAT) : O_RDONLY;
    int fd = ::open(path.c_str(), flags, 0644);
    if (fd >= 0) fd_cache[path] = FdEntry{fd, write ? 1 : 0, 0};
    return fd;
  }

  void uring_worker() {
    // CQ holds 2x SQ entries; never let unreaped completions exceed it
    const int64_t max_kernel = (int64_t)ring.entries * 2;
    for (;;) {
      std::vector<Chunk> batch;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] {
          return stop || !queue.empty() || kernel_inflight > 0;
        });
        if (stop && queue.empty() && kernel_inflight == 0) return;
        int64_t budget = max_kernel - kernel_inflight;
        unsigned space = ring.sq_space();
        while (!queue.empty() && (int64_t)batch.size() < budget &&
               batch.size() < space) {
          batch.push_back(std::move(queue.front()));
          queue.pop_front();
        }
      }
      unsigned submitted = 0;
      for (auto& c : batch) {
        int fd = get_fd(c.path, c.op == 1);
        if (fd == -2) {  // fd busy in the wrong mode: retry next round
          std::lock_guard<std::mutex> lk(mu);
          queue.push_back(std::move(c));
          cv.notify_all();
          continue;
        }
        if (fd < 0) {
          finish_chunk(false);
          continue;
        }
        uint64_t tok = next_token++;
        ring.push_sqe(c.op, fd, c.buf, (unsigned)c.nbytes, c.offset, tok);
        fd_cache[c.path].in_kernel++;
        pending_path.emplace(tok, c.path);
        pending.emplace(tok, std::move(c));
        ++submitted;
        {
          std::lock_guard<std::mutex> lk(mu);
          ++kernel_inflight;
        }
      }
      bool want_events;
      {
        std::lock_guard<std::mutex> lk(mu);
        want_events = kernel_inflight > 0;
      }
      if (submitted || want_events)
        sys_io_uring_enter(ring.ring_fd, submitted, want_events ? 1 : 0,
                           IORING_ENTER_GETEVENTS);
      // reap completions
      unsigned head = __atomic_load_n(ring.cq_head, __ATOMIC_ACQUIRE);
      unsigned tail = __atomic_load_n(ring.cq_tail, __ATOMIC_ACQUIRE);
      while (head != tail) {
        struct io_uring_cqe* cqe = &ring.cqes[head & *ring.cq_mask];
        auto it = pending.find(cqe->user_data);
        if (it != pending.end()) {
          Chunk c = std::move(it->second);
          pending.erase(it);
          auto pp = pending_path.find(cqe->user_data);
          if (pp != pending_path.end()) {
            auto fe = fd_cache.find(pp->second);
            if (fe != fd_cache.end()) fe->second.in_kernel--;
            pending_path.erase(pp);
          }
          {
            std::lock_guard<std::mutex> lk(mu);
            --kernel_inflight;
          }
          int32_t res = cqe->res;
          if (res <= 0) {
            finish_chunk(false);
          } else if (res < c.nbytes) {
            // short op: resubmit the remainder
            c.buf += res;
            c.nbytes -= res;
            c.offset += res;
            std::lock_guard<std::mutex> lk(mu);
            queue.push_back(std::move(c));
            cv.notify_all();
          } else {
            finish_chunk(true);
          }
        }
        ++head;
      }
      __atomic_store_n(ring.cq_head, head, __ATOMIC_RELEASE);
    }
  }
#endif  // DS_HAVE_IO_URING

  void worker() {
    for (;;) {
      Chunk c;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        c = std::move(queue.front());
        queue.pop_front();
      }
      bool ok = run(c);
      {
        std::lock_guard<std::mutex> lk(mu);
        if (!ok) ++errors;
        if (--inflight == 0) done_cv.notify_all();
      }
    }
  }

  static bool run(const Chunk& c) {
    int flags = (c.op == 0) ? O_RDONLY : (O_WRONLY | O_CREAT);
    int fd = ::open(c.path.c_str(), flags, 0644);
    if (fd < 0) return false;
    int64_t done = 0;
    bool ok = true;
    while (done < c.nbytes) {
      ssize_t r = (c.op == 0)
                      ? ::pread(fd, c.buf + done, c.nbytes - done,
                                c.offset + done)
                      : ::pwrite(fd, c.buf + done, c.nbytes - done,
                                 c.offset + done);
      if (r <= 0) {
        ok = false;
        break;
      }
      done += r;
    }
    ::close(fd);
    return ok;
  }

  void submit(int op, const char* path, void* buf, int64_t nbytes,
              int64_t offset) {
    std::lock_guard<std::mutex> lk(mu);
    for (int64_t off = 0; off < nbytes; off += block_size) {
      int64_t len = std::min(block_size, nbytes - off);
      queue.push_back(Chunk{op, path, (char*)buf + off, len, offset + off});
      ++inflight;
    }
    cv.notify_all();
  }

  int64_t wait() {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [&] { return inflight == 0; });
    int64_t e = errors;
    errors = 0;
    return e;
  }
};

}  // namespace

extern "C" {

void* ds_aio_create(int64_t block_size, int n_threads) {
  Handle* h = new Handle();
  h->block_size = block_size > 0 ? block_size : (1 << 20);
  h->n_threads = n_threads > 0 ? n_threads : 1;
#ifdef DS_HAVE_IO_URING
  // prefer io_uring (queue depth scales with thread request, capped 256)
  unsigned depth = 64;
  while ((int)depth < h->n_threads * 16 && depth < 256) depth <<= 1;
  if (h->ring.init(depth)) h->use_uring = true;
#endif
  if (h->use_uring) {
#ifdef DS_HAVE_IO_URING
    h->workers.emplace_back([h] { h->uring_worker(); });
#endif
  } else {
    for (int i = 0; i < h->n_threads; ++i)
      h->workers.emplace_back([h] { h->worker(); });
  }
  return h;
}

void ds_aio_destroy(void* hp) {
  Handle* h = (Handle*)hp;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->stop = true;
  }
  h->cv.notify_all();
  for (auto& t : h->workers) t.join();
#ifdef DS_HAVE_IO_URING
  for (auto& kv : h->fd_cache) ::close(kv.second.fd);
  if (h->use_uring) h->ring.close_all();
#endif
  delete h;
}

// which engine is live: 1 = io_uring, 0 = thread pool
int ds_aio_engine(void* hp) { return ((Handle*)hp)->use_uring ? 1 : 0; }

// async positional read/write; call ds_aio_wait to drain.
void ds_aio_pread(void* hp, const char* path, void* buf, int64_t nbytes,
                  int64_t offset) {
  ((Handle*)hp)->submit(0, path, buf, nbytes, offset);
}

void ds_aio_pwrite(void* hp, const char* path, void* buf, int64_t nbytes,
                   int64_t offset) {
  ((Handle*)hp)->submit(1, path, buf, nbytes, offset);
}

// returns the number of failed chunks since the previous wait (0 = success).
int64_t ds_aio_wait(void* hp) { return ((Handle*)hp)->wait(); }

// blocking whole-file helpers (reference aio_handle.read/write).
int64_t ds_aio_read_sync(void* hp, const char* path, void* buf,
                         int64_t nbytes) {
  Handle* h = (Handle*)hp;
  h->submit(0, path, buf, nbytes, 0);
  return h->wait();
}

int64_t ds_aio_write_sync(void* hp, const char* path, void* buf,
                          int64_t nbytes) {
  Handle* h = (Handle*)hp;
  h->submit(1, path, buf, nbytes, 0);
  return h->wait();
}

int64_t ds_aio_file_size(const char* path) {
  struct stat st;
  if (::stat(path, &st) != 0) return -1;
  return (int64_t)st.st_size;
}

}  // extern "C"
