// SIMD host-side optimizer steps for offloaded optimizer states.
//
// TPU-native equivalent of the reference's csrc/adam (cpu_adam_impl.cpp,
// AVX2/AVX512 Adam_Optimizer in csrc/includes/cpu_adam.h:24), csrc/adagrad
// (cpu_adagrad.cpp) and csrc/lion (cpu_lion_impl.cpp): when optimizer states
// live in host memory (ZeRO-Offload analog), the update runs on the host CPU
// while the TPU computes the next micro-batches. The reference hand-codes
// AVX intrinsics; here each loop is written so the compiler auto-vectorizes
// (-O3 -march=native -ffast-math) and OpenMP splits across cores — same
// machine code class, no intrinsics to port per-ISA.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
// All buffers are contiguous float32; callers hand in raw pointers.

#include <cmath>
#include <cstdint>

extern "C" {

// Fused Adam / AdamW (reference csrc/adam/cpu_adam_impl.cpp Step_1/4/8).
void ds_adam_step(float* p, const float* g, float* m, float* v, int64_t n,
                  float lr, float beta1, float beta2, float eps,
                  float weight_decay, int step, int adamw_mode,
                  int bias_correction) {
  float bc1 = 1.0f, bc2_sqrt = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - powf(beta1, (float)step);
    bc2_sqrt = sqrtf(1.0f - powf(beta2, (float)step));
  }
  const float step_size = lr / bc1;
  const float b1m = 1.0f - beta1, b2m = 1.0f - beta2;
#pragma omp parallel for simd
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i];
    if (!adamw_mode) grad += weight_decay * p[i];
    m[i] = beta1 * m[i] + b1m * grad;
    v[i] = beta2 * v[i] + b2m * grad * grad;
    float denom = sqrtf(v[i]) / bc2_sqrt + eps;
    // decoupled decay scales by lr alone, NOT lr/bias_correction
    float decay = adamw_mode ? lr * weight_decay * p[i] : 0.0f;
    p[i] -= step_size * (m[i] / denom) + decay;
  }
}

// Adagrad (reference csrc/adagrad/cpu_adagrad.cpp).
void ds_adagrad_step(float* p, const float* g, float* h, int64_t n, float lr,
                     float eps, float weight_decay) {
#pragma omp parallel for simd
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i] + weight_decay * p[i];
    h[i] += grad * grad;
    p[i] -= lr * grad / (sqrtf(h[i]) + eps);
  }
}

// Lion (reference csrc/lion/cpu_lion_impl.cpp).
void ds_lion_step(float* p, const float* g, float* m, int64_t n, float lr,
                  float beta1, float beta2, float weight_decay) {
  const float b1m = 1.0f - beta1, b2m = 1.0f - beta2;
#pragma omp parallel for simd
  for (int64_t i = 0; i < n; ++i) {
    float c = beta1 * m[i] + b1m * g[i];
    float sign = (c > 0.0f) ? 1.0f : ((c < 0.0f) ? -1.0f : 0.0f);
    p[i] -= lr * (sign + weight_decay * p[i]);
    m[i] = beta2 * m[i] + b2m * g[i];
  }
}

// SGD with momentum — host fallback path for completeness.
void ds_sgd_step(float* p, const float* g, float* m, int64_t n, float lr,
                 float momentum, float weight_decay) {
#pragma omp parallel for simd
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i] + weight_decay * p[i];
    m[i] = momentum * m[i] + grad;
    p[i] -= lr * m[i];
  }
}

// bf16<->fp32 pack/unpack for host-resident low-precision shadows
// (reference csrc/utils/tensor_cast.cpp).
void ds_bf16_to_fp32(const uint16_t* src, float* dst, int64_t n) {
#pragma omp parallel for simd
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits = ((uint32_t)src[i]) << 16;
    float f;
    __builtin_memcpy(&f, &bits, 4);
    dst[i] = f;
  }
}

void ds_fp32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
#pragma omp parallel for simd
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits;
    __builtin_memcpy(&bits, &src[i], 4);
    // round-to-nearest-even
    uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
    dst[i] = (uint16_t)((bits + rounding) >> 16);
  }
}

}  // extern "C"
