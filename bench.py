#!/usr/bin/env python
"""Headline benchmark: Llama-style causal-LM training step throughput + MFU.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Baseline (BASELINE.md): the reference's ZeRO-3 north-star is >=45% MFU; we
report our measured model-flops-utilization against that target.

Robustness (VERDICT r1 weak #1): backend bring-up is retried, falls back to
CPU with an explicit degraded marker, and a JSON line is ALWAYS printed —
even on failure — so no round ships zero perf evidence.
"""

import json
import os
import sys
import time
import traceback

_HERE = os.path.dirname(os.path.abspath(__file__))
# one-time path setup (scripts/ holds the shared probe finalizer) — emit()
# used to re-insert this on every call, growing sys.path per emission
for _p in (_HERE, os.path.join(_HERE, "scripts")):
    if _p not in sys.path:
        sys.path.insert(0, _p)

RESULT = {
    "metric": "llama_zero3_train_mfu",
    "value": 0.0,
    "unit": "fraction_of_peak",
    "vs_baseline": 0.0,
    "detail": {},
}


def emit(ok: bool, err: str = ""):
    if err:
        RESULT["detail"]["error"] = err[-2000:]
    # a failed subprobe must poison the ok flag (VERDICT r4 item 4b: a
    # failed decode row shipped inside an ok:true capture) — budget skips
    # are not failures. ONE failure rule, shared with every probe script
    # (scripts/ is on sys.path from module import).
    from _probe_common import _bad
    subprobes = {k: RESULT["detail"].get(k)
                 for k in ("decode_tok_per_sec", "shape_mfu", "attn_probe",
                           "remat_sweep", "overlap_remat")
                 if k in RESULT["detail"]}
    RESULT["detail"]["ok"] = ok and not _bad(subprobes)
    attach_live_evidence()
    print(json.dumps(RESULT))


# every watcher-promoted capture slot and its detail key — the test suite
# iterates this same constant, so adding a slot is one edit
LIVE_CAPTURE_SLOTS = (
    ("BENCH_TPU_LIVE.json", "tpu_capture"),
    ("LONGCTX_TPU_LIVE.json", "tpu_longctx_capture"),
    ("SERVING_TPU_LIVE.json", "tpu_serving_capture"),
    ("MOE_TPU_LIVE.json", "tpu_moe_dispatch_capture"),
    ("QUANT_TPU_LIVE.json", "tpu_quant_linear_capture"),
    ("KERNELS_TPU_LIVE.json", "tpu_kernel_sanity_capture"),
    ("ATTN_TPU_LIVE.json", "tpu_attn_sweep_capture"),
)


def attach_live_evidence(base_dir: str = None):
    """If this run could not reach the TPU but the in-round tunnel watcher
    (scripts/tpu_watch.sh) captured a full TPU bench in an earlier working
    window, embed that capture — clearly labeled with its timestamp — so a
    round whose tunnel is down at driver time still ships the real-chip
    numbers. The headline value stays the honest current-run number."""
    if "tpu" in str(RESULT["detail"].get("backend", "")):
        return  # live TPU run; nothing to attach
    here = base_dir or os.path.dirname(os.path.abspath(__file__))
    for name, key in LIVE_CAPTURE_SLOTS:
        path = os.path.join(here, name)
        try:
            with open(path) as f:
                cap = json.loads(f.read().strip().splitlines()[-1])
            cap["captured_at_utc"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(path)))
            cap["note"] = ("captured mid-round by scripts/tpu_watch.sh in a "
                           "working tunnel window; current run's tunnel was down")
            RESULT["detail"][key] = cap
        except Exception:
            pass  # no capture this round — nothing to attach


CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".xla_cache")


def probe_backend(attempts: int = 5) -> str:
    """Probe the accelerator ONCE, up front, in subprocesses; on failure set
    ``DSTPU_BENCH_FORCE_CPU`` so every later stage — the decode child AND this
    process's backend init — skips re-probing. (Round-2 failure mode: the
    decode child burned its entire 600s budget re-running these probes while
    the tunnel was wedged, so decode never emitted a number.)

    JAX caches backend init results in-process (a failed TPU probe leaves a
    CPU-only cache that later jax.devices() calls silently return), so each
    probe is a SUBPROCESS; jax is only imported in-process after the verdict.
    """
    import subprocess

    if os.environ.get("DSTPU_BENCH_FORCE_CPU"):
        return ""  # explicit degraded run (CI/smoke); skip the probe
    if os.environ.get("DSTPU_BENCH_BACKEND"):
        return os.environ["DSTPU_BENCH_BACKEND"]  # parent already probed OK
    probe = ("import jax; d = jax.devices(); "
             "print(jax.default_backend(), len(d))")
    for attempt in range(attempts):
        try:
            r = subprocess.run([sys.executable, "-c", probe],
                               capture_output=True, text=True, timeout=180)
            err = r.stderr[-500:]
            if r.returncode == 0 and r.stdout.strip():
                backend = r.stdout.strip().split()[-2]
                os.environ["DSTPU_BENCH_BACKEND"] = backend
                return backend
        except subprocess.TimeoutExpired:
            err = "probe timed out after 180s (tunnel wedged?)"
        sys.stderr.write(
            f"backend probe attempt {attempt + 1} failed:\n{err}\n")
        if attempt < attempts - 1:
            time.sleep(10 * (attempt + 1))
    os.environ["DSTPU_BENCH_FORCE_CPU"] = "1"
    return ""


def init_backend():
    """Import jax on the backend ``probe_backend`` decided (CPU-degraded when
    the probe failed), with the persistent compilation cache enabled so a
    re-run after a tunnel blip skips the multi-minute compiles."""
    if os.environ.get("DSTPU_BENCH_FORCE_CPU"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        RESULT["detail"]["backend"] = "cpu-degraded"
    else:
        import jax

        actual = jax.default_backend()
        expected = os.environ.get("DSTPU_BENCH_BACKEND", actual)
        # the tunnel can wedge between the up-front probe and this import
        # (the decode child holds that window open for up to 600s); a silent
        # CPU fallback must not masquerade as a healthy accelerator run
        RESULT["detail"]["backend"] = (
            actual if actual == expected else f"{actual}-degraded")
    try:
        jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass  # cache is an optimization, never a failure
    RESULT["detail"]["n_chips"] = len(jax.devices())
    return jax


def peak_flops_per_chip(jax) -> float:
    """bf16 peak for the local accelerator."""
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 2e12  # CPU smoke-run placeholder


def model_flops_per_token(mcfg, seqlen: int) -> float:
    """Model flops per token: 6*N (fwd+bwd matmuls) + the causal-attention
    term 12*L*H*S. Shared by the headline and shape-row MFU so the two
    numbers stay comparable."""
    return (6 * mcfg.num_params
            + 12 * mcfg.num_layers * mcfg.hidden_size * seqlen)


def bench_model_config(on_tpu: bool, remat: bool = False):
    """ONE model for both the train-MFU and decode benches — keep these in
    sync or the decode number describes a different model."""
    from deepspeed_tpu.models import llama

    if not on_tpu:
        return llama.LlamaConfig.tiny()
    # 235M-param Llama (head_dim=128: MXU-native; hd=64 costs ~25% MFU)
    return llama.LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=3584,
        num_layers=12, num_heads=8, num_kv_heads=4, max_seq_len=2048,
        rope_theta=500000.0, remat=remat)


def bench_shape_rows(jax, budget_s: float = None) -> dict:
    """MFU at the north-star shapes (VERDICT r2: prove the 8B-class rows):
    few-layer Llama train steps at h=1024/2048/4096, hd=64 vs hd=128 — the
    headline config must not be the only (flattering) row. Runs inside a
    wall-clock budget; rows that don't fit are reported as 'skipped'."""
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu as dst
    from deepspeed_tpu.comm import mesh as mesh_lib
    from deepspeed_tpu.models import llama

    if budget_s is None:
        budget_s = float(os.environ.get("DSTPU_BENCH_SHAPE_BUDGET_S", 1500))
    t_start = time.perf_counter()
    # (label, hidden, inter, layers, heads, kv, head_dim)
    configs = [
        ("h1024_hd64", 1024, 3584, 12, 16, 8, 64),
        ("h1024_hd128", 1024, 3584, 12, 8, 4, 128),
        ("h2048_hd128", 2048, 7168, 6, 16, 8, 128),
        ("h4096_hd128", 4096, 14336, 2, 32, 8, 128),  # Llama-3-8B layer
    ]
    rows = {}
    n_chips = max(1, len(jax.devices()))
    batch = int(os.environ.get("DSTPU_BENCH_SHAPE_BATCH", 4 * n_chips))
    seqlen = int(os.environ.get("DSTPU_BENCH_SHAPE_SEQLEN", 2048))
    steps = int(os.environ.get("DSTPU_BENCH_SHAPE_STEPS", 8))
    peak = peak_flops_per_chip(jax)
    engine = None
    for label, h, inter, L, nh, nkv, hd in configs:
        if time.perf_counter() - t_start > budget_s:
            rows[label] = "skipped: shape budget exhausted"
            continue
        try:
            engine = None  # free the previous row's params/opt state first
            mesh_lib.set_mesh(None)
            mcfg = llama.LlamaConfig(
                vocab_size=32000, hidden_size=h, intermediate_size=inter,
                num_layers=L, num_heads=nh, num_kv_heads=nkv, head_dim=hd,
                max_seq_len=seqlen, rope_theta=500000.0, remat=True)
            spec = llama.model_spec(mcfg, compute_dtype=jnp.bfloat16)
            engine, _, _, _ = dst.initialize(model=spec, config={
                "train_batch_size": batch,
                "bf16": {"enabled": True},
                "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
                "zero_optimization": {"stage": 3},
                "steps_per_print": 0,
            })
            rng = np.random.default_rng(0)
            toks = {"tokens": rng.integers(
                0, mcfg.vocab_size, (batch, seqlen + 1), dtype=np.int32)}
            float(engine.train_batch(toks).loss)  # compile + warm
            t0 = time.perf_counter()
            for _ in range(steps):
                out = engine.train_batch(toks)
            float(out.loss)
            dt = (time.perf_counter() - t0) / steps
            tps_per_chip = batch * seqlen / dt / n_chips
            flops_tok = model_flops_per_token(mcfg, seqlen)
            rows[label] = {"mfu": round(tps_per_chip * flops_tok / peak, 4),
                           "tok_per_sec_per_chip": round(tps_per_chip, 1),
                           "params_m": round(mcfg.num_params / 1e6, 1),
                           "step_s": round(dt, 3)}
            sys.stderr.write(f"[bench] shape {label}: {rows[label]}\n")
        except Exception as e:  # one bad shape must not kill the rest
            rows[label] = f"error: {str(e)[-200:]}"
    return rows


def bench_attention_probe(jax) -> dict:
    """Standalone attention MFU at hd=128 with the 512-wide flash block —
    the PERF.md open item ("not yet re-measured standalone"; expected ~2×
    the hd=64 rows). fwd and fwd+bwd, amortized inside one jit (same recipe
    as scripts/attn_sweep.py; flops: causal fwd = 2·B·H·S²·D, fwd+bwd =
    3.5×). Runs in every tpu_watch.sh window via the headline bench.

    GQA sweep (ISSUE 14; docs/performance.md "Native GQA attention"):
    kv_heads ∈ {1, 4, 8, nq} ∩ divisors(nq) at the same shape, widened vs
    ``attention.gqa_native`` narrow kernels, with per-step attention KV HBM
    bytes accounted (bytes of the K/V operands the kernels stream; the
    widened path's are nq/nkv× larger in fwd AND bwd). The native rows
    additionally assert — by counting ``ops.attention.repeat_kv`` widening
    calls at trace time — that no q-width KV copy exists, so
    ``kv_bytes_saved`` is measured program structure, not an assumption.
    ``Train/attn/{kv_bytes_saved,gqa_ratio}`` gauges ride a TelemetryHub."""
    import jax.numpy as jnp
    from jax import lax

    import importlib

    # the ops package re-exports the `attention` dispatcher under the same
    # name, shadowing the submodule on attribute access
    attn_mod = importlib.import_module("deepspeed_tpu.ops.attention")
    from deepspeed_tpu.ops.pallas import flash_attention as fa

    on_tpu = "tpu" in str(RESULT["detail"].get("backend", ""))
    peak = peak_flops_per_chip(jax)
    B, H, D = (8, 8, 128) if on_tpu else (1, 2, 128)
    S = 2048 if on_tpu else 256
    blk = 512 if on_tpu else 128
    rows = {"shape": f"B{B}_H{H}_S{S}_hd{D}_bq{blk}"}
    old_blk = os.environ.get("DSTPU_FLASH_BLOCK")
    os.environ["DSTPU_FLASH_BLOCK"] = str(blk)

    def measure(q, k, v, mode):
        """(ms, mfu) for one config — chained reps inside one jit."""
        fwd_flops = 2 * B * H * S * S * D
        if mode == "fwd":
            flops = fwd_flops

            def op(k, v, q):
                return fa.flash_attention(q, k, v, causal=True)
        else:
            flops = int(3.5 * fwd_flops)

            def loss(q, k, v):
                o = fa.flash_attention(q, k, v, causal=True)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            def op(k, v, q):
                return jax.grad(lambda q: loss(q, k, v))(q)

        reps, steps = (10, 3) if on_tpu else (2, 1)

        def chained(k, v, q0):
            def body(carry, _):
                return op(k, v, carry), ()

            out, _ = lax.scan(body, q0, None, length=reps)
            return out

        f = jax.jit(chained)
        out = f(k, v, q)
        float(jnp.sum(out.astype(jnp.float32)))  # compile + sync
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(k, v, q)
        float(jnp.sum(out.astype(jnp.float32)))
        dt = (time.perf_counter() - t0) / (steps * reps)
        return round(dt * 1e3, 3), round(flops / dt / peak, 4)

    try:
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D),
                              jnp.bfloat16)
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D),
                              jnp.bfloat16)
        for mode in ("fwd", "fwdbwd"):
            ms, mfu = measure(q, k, k, mode)
            rows[mode] = {"ms": ms, "mfu": mfu}

        # --- GQA sweep: same q, kv-head-narrow K/V, widened vs native ---
        gqa = {}
        rows["gqa"] = gqa
        elem = 2  # bf16 K/V
        passes = {"fwd": 1, "fwdbwd": 3}  # fwd + dq + dkv each stream K/V
        real_repeat = attn_mod.repeat_kv
        best = None
        for kvh in sorted(x for x in {1, 4, 8, H} if H % x == 0 and x <= H):
            kn = jax.random.normal(jax.random.PRNGKey(2), (B, S, kvh, D),
                                   jnp.bfloat16)
            vn = jax.random.normal(jax.random.PRNGKey(3), (B, S, kvh, D),
                                   jnp.bfloat16)
            row = {"ratio": H // kvh}
            for native in (False, True):
                prev = attn_mod.configure_gqa_native(native)
                widens = [0]

                def counting_repeat(x, nq):
                    if x.shape[-2] != nq:
                        widens[0] += 1
                    return real_repeat(x, nq)

                attn_mod.repeat_kv = counting_repeat
                try:
                    sub = {}
                    for mode in ("fwd", "fwdbwd"):
                        widens[0] = 0
                        ms, mfu = measure(q, kn, vn, mode)
                        kvh_eff = kvh if native and kvh != H else H
                        sub[mode] = {
                            "ms": ms, "mfu": mfu,
                            "kv_bytes": 2 * B * S * kvh_eff * D * elem
                            * passes[mode],
                            "widen_calls": widens[0]}
                    if native and kvh != H:
                        # measured program structure: the narrow path must
                        # contain ZERO q-width KV widenings
                        assert sub["fwd"]["widen_calls"] == 0 and \
                            sub["fwdbwd"]["widen_calls"] == 0, \
                            f"native kv{kvh}: widen leaked {sub}"
                    row["native" if native else "widened"] = sub
                finally:
                    attn_mod.repeat_kv = real_repeat
                    attn_mod.configure_gqa_native(prev)
            saved = (row["widened"]["fwdbwd"]["kv_bytes"]
                     - row["native"]["fwdbwd"]["kv_bytes"])
            row["kv_bytes_saved_fwdbwd"] = saved
            gqa[f"kv{kvh}"] = row
            if kvh != H and (best is None or saved > best[0]):
                best = (saved, H // kvh)
        if best is not None:
            try:  # Train/attn/* gauges (closed TRAIN_SERIES registry)
                from deepspeed_tpu.telemetry.hub import TelemetryHub

                hub = TelemetryHub(None)
                hub.train_event("attn/kv_bytes_saved", float(best[0]))
                hub.train_event("attn/gqa_ratio", float(best[1]))
            except Exception:
                pass
    except Exception as e:  # a failed probe must not kill the headline
        rows["error"] = str(e)[-300:]
    finally:
        if old_blk is None:
            os.environ.pop("DSTPU_FLASH_BLOCK", None)
        else:
            os.environ["DSTPU_FLASH_BLOCK"] = old_blk
    return rows


# every policy the sweep measures — mirrors telemetry.schema.REMAT_POLICIES
# minus the offload/no-batch-dim variants (not step-time-relevant on the
# bench shape; offload needs real pinned host memory to mean anything)
REMAT_SWEEP_POLICIES = ("none", "full", "dots_saveable", "save_attn_out",
                        "save_big_matmuls")


def _remat_engine(jax, on_tpu, policy, overlap=False, mcfg=None):
    import jax.numpy as jnp

    import deepspeed_tpu as dst
    from deepspeed_tpu.comm import mesh as mesh_lib
    from deepspeed_tpu.models import llama

    import dataclasses

    mesh_lib.set_mesh(None)
    mcfg = dataclasses.replace(mcfg or bench_model_config(on_tpu),
                               remat=policy != "none", remat_policy=policy)
    config = {
        "train_batch_size": 8 * max(1, len(jax.devices())),
        "bf16": {"enabled": True},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
        "zero_optimization": {"stage": 3},
        "steps_per_print": 0,
    }
    if overlap:
        config["comms_overlap"] = {"enabled": True, "layer_prefetch": True}
    spec = llama.model_spec(mcfg, compute_dtype=jnp.bfloat16)
    engine, _, _, _ = dst.initialize(model=spec, config=config)
    return engine, mcfg


def _block_saved_bytes(mcfg, policy) -> object:
    """Trace-time saved-residual bytes of ONE transformer block under the
    policy (exact, device-free) — the honest per-policy memory number the
    allocator can't give (its peak is a process-global running max)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.models import llama
    from deepspeed_tpu.ops.rotary import rope_frequencies
    from deepspeed_tpu.runtime.activation_checkpointing import (
        checkpointing as ac)

    params = llama.init(mcfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    layer0 = jax.tree.map(lambda a: a[0], params["layers"])
    cos, sin = rope_frequencies(mcfg.head_size, mcfg.max_seq_len,
                                mcfg.rope_theta)
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (1, min(256, mcfg.max_seq_len), mcfg.hidden_size)), jnp.bfloat16)

    def blk(x):
        return jnp.sum(
            llama._block(mcfg, x, layer0, cos, sin, None).astype(jnp.float32)
            ** 2)

    return ac.saved_bytes(blk, x, policy=policy)


def bench_remat_sweep(jax, on_tpu, steps=None) -> dict:
    """Per-remat-policy HBM-vs-step-time sweep (the measured, not asserted,
    memory/speed trade): step time on the bench config, compiled temp bytes
    (memory_analysis — the activation footprint remat actually moves),
    MemoryTelemetry allocator/live-bytes snapshot, and exact per-block
    saved-residual bytes. Rows land in the headline JSON and as
    ``Train/remat/*`` gauges through the engine's TelemetryHub."""
    import numpy as np

    from deepspeed_tpu.telemetry.memory import MemoryTelemetry

    budget_s = float(os.environ.get("DSTPU_BENCH_REMAT_BUDGET_S",
                                    900 if on_tpu else 240))
    t_start = time.perf_counter()
    if steps is None:
        steps = 8 if on_tpu else 3
    seqlen = 2048 if on_tpu else 128
    rows = {}
    for policy in REMAT_SWEEP_POLICIES:
        if time.perf_counter() - t_start > budget_s:
            rows[policy] = "skipped: remat sweep budget exhausted"
            continue
        try:
            engine, mcfg = _remat_engine(jax, on_tpu, policy)
            rng = np.random.default_rng(0)
            toks = {"tokens": rng.integers(
                0, mcfg.vocab_size,
                (engine.train_batch_size(), seqlen + 1), dtype=np.int32)}
            float(engine.train_batch(toks).loss)  # compile + warm
            t0 = time.perf_counter()
            for _ in range(steps):
                out = engine.train_batch(toks)
            float(out.loss)
            dt = (time.perf_counter() - t0) / steps
            row = {"step_s": round(dt, 4)}
            try:  # compiled temp bytes: the footprint remat moves
                batch = engine._shard_batch(toks, with_gas_dim=True)
                mem = engine._train_step.lower(
                    engine.state, batch,
                    engine._lr_override).compile().memory_analysis()
                row["temp_bytes"] = int(mem.temp_size_in_bytes)
            except Exception:
                pass
            snap = MemoryTelemetry().snapshot()
            row["hbm_in_use"] = int(snap["bytes_in_use"])
            row["hbm_peak"] = int(snap["peak_bytes"])
            saved = _block_saved_bytes(mcfg, policy)
            if saved is not None:
                row["block_saved_bytes"] = int(saved)
            rows[policy] = row
            hub = getattr(engine, "telemetry", None)
            if hub is not None:
                hub.train_event(f"remat/step_ms_{policy}", dt * 1e3)
                if saved is not None:
                    hub.train_event(f"remat/saved_bytes_{policy}",
                                    float(saved))
                hub.train_event(f"remat/peak_bytes_{policy}",
                                float(row.get("temp_bytes",
                                              row["hbm_peak"])))
            sys.stderr.write(f"[bench] remat {policy}: {rows[policy]}\n")
        except Exception as e:  # one bad policy must not kill the sweep
            rows[policy] = f"error: {str(e)[-200:]}"
    return rows


def bench_overlap_remat(jax, on_tpu, steps=None) -> dict:
    """The combined fine-grained-overlap + selective-remat config vs the
    pre-PR default (full remat, no overlap) on the SAME model/step budget —
    the acceptance comparison. On the CPU proxy the win comes from skipping
    the big-matmul recompute; on silicon the layer_prefetch all-gather
    overlap stacks on top (verified via tpu_watch.sh captures)."""
    import numpy as np

    from deepspeed_tpu.comm import overlap as ov
    from deepspeed_tpu.models import llama

    if on_tpu:
        base_cfg, seqlen = bench_model_config(True), 2048
        steps = steps or 10
    else:
        # CPU proxy: wide enough (h=512) that the skipped big-matmul
        # recompute dominates the per-layer prefetch slice overhead — the
        # tiny 2-layer headline config is timing-noise-bound here
        # (measured: save_big_matmuls + prefetch beats full remat ~5% in
        # every interleaved window at this shape)
        base_cfg = llama.LlamaConfig(
            vocab_size=256, hidden_size=512, intermediate_size=1024,
            num_layers=4, num_heads=8, num_kv_heads=4, max_seq_len=512,
            rope_theta=10000.0)
        seqlen, steps = 256, steps or 3
    variants = (("baseline_full_remat", "full", False),
                ("overlap_selective_remat", "save_big_matmuls", True))
    out = {}
    try:
        engines = {}
        for label, policy, overlap in variants:
            engine, mcfg = _remat_engine(jax, on_tpu, policy,
                                         overlap=overlap, mcfg=base_cfg)
            rng = np.random.default_rng(0)
            toks = {"tokens": rng.integers(
                0, mcfg.vocab_size,
                (engine.train_batch_size(), seqlen + 1), dtype=np.int32)}
            float(engine.train_batch(toks).loss)  # compile + warm
            engines[label] = (engine, toks)
        # interleaved best-of-3 windows: the two programs are near-identical
        # and the proxy host is noisy, so A/B/A/B windows + min cancel load
        # swings a sequential measurement would alias into the comparison
        best = {label: None for label, _, _ in variants}
        for _ in range(3):
            for label, _, _ in variants:
                engine, toks = engines[label]
                t0 = time.perf_counter()
                for _ in range(steps):
                    o = engine.train_batch(toks)
                float(o.loss)
                dt = (time.perf_counter() - t0) / steps
                if best[label] is None or dt < best[label]:
                    best[label] = dt
                out[label] = {"step_s": round(best[label], 4),
                              "final_loss": round(float(o.loss), 4)}
        ov.reset_layer_prefetch()
        base = out["baseline_full_remat"]["step_s"]
        tuned = out["overlap_selective_remat"]["step_s"]
        if tuned > 0:
            out["speedup"] = round(base / tuned, 3)
    except Exception as e:
        out["error"] = str(e)[-300:]
    return out


def _bench_result_from_file(path: str):
    """Extract the bench RESULT object from any BENCH artifact shape: a raw
    bench stdout capture (the JSON line is last), a promoted *_TPU_LIVE
    file, or a round wrapper ``{"n", "cmd", "rc", "tail"}`` with the JSON
    line embedded in ``tail``."""
    def scan_lines(text):
        for line in reversed(text.strip().splitlines()):
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if isinstance(d, dict) and "metric" in d and "detail" in d:
                    return d
        return None

    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    try:
        doc = json.loads(text)
    except ValueError:
        return scan_lines(text)
    if isinstance(doc, dict) and "metric" in doc and "detail" in doc:
        return doc
    if isinstance(doc, dict) and "tail" in doc:
        return scan_lines(str(doc["tail"]))
    return None


def find_newest_bench_artifact(base_dir: str = None):
    """Newest checked-in round artifact (``BENCH_r<NN>.json`` with the
    highest round number) — the reference the regression mode compares a
    fresh run against. Returns a path or None. ``DSTPU_BENCH_REF_DIR``
    overrides the search directory (tests, out-of-tree comparisons)."""
    import glob
    import re

    here = base_dir or os.environ.get("DSTPU_BENCH_REF_DIR") \
        or os.path.dirname(os.path.abspath(__file__))
    best_path, best_n = None, -1
    for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m and int(m.group(1)) > best_n:
            best_path, best_n = p, int(m.group(1))
    return best_path


def compare_step_time(fresh: dict, ref: dict, pct: float) -> dict:
    """Pure compare: fresh vs reference ``detail.step_time_s``, matched by
    backend class (a CPU-degraded run must never be judged against a TPU
    capture). A TPU-backed fresh run may fall back to the reference's
    embedded ``detail.tpu_capture``. ``fail`` = fresh step time more than
    ``pct`` percent above the reference."""
    def is_tpu(d):
        return "tpu" in str(d.get("detail", {}).get("backend", ""))

    def step_s(d):
        try:
            return float(d["detail"]["step_time_s"])
        except (KeyError, TypeError, ValueError):
            return 0.0

    row = {"threshold_pct": pct, "reference": "headline"}
    ref_d = ref
    if is_tpu(fresh) != is_tpu(ref):
        cap = ref.get("detail", {}).get("tpu_capture")
        if is_tpu(fresh) and isinstance(cap, dict) and is_tpu(cap):
            ref_d, row["reference"] = cap, "tpu_capture"
        else:
            row["status"] = ("skipped: backend mismatch (fresh="
                             f"{fresh.get('detail', {}).get('backend')} ref="
                             f"{ref.get('detail', {}).get('backend')})")
            return row
    fs, rs = step_s(fresh), step_s(ref_d)
    if fs <= 0 or rs <= 0:
        row["status"] = "skipped: missing step_time_s"
        return row
    row.update({"fresh_step_s": round(fs, 4), "ref_step_s": round(rs, 4),
                "delta_pct": round((fs / rs - 1.0) * 100, 1),
                "fail": fs > rs * (1.0 + pct / 100.0)})
    row["status"] = "regressed" if row["fail"] else "ok"
    return row


def step_time_regression(base_dir: str = None, fresh: dict = None) -> dict:
    """Regression row vs the newest ``BENCH_r*.json``. Non-fatal by design:
    this documents the trajectory inside the artifact (and powers the
    ``--regression-only`` probe); it never poisons ``detail.ok``."""
    pct = float(os.environ.get("DSTPU_BENCH_REGRESSION_PCT", 20))
    ref_path = find_newest_bench_artifact(base_dir)
    if ref_path is None:
        return {"status": "skipped: no BENCH_r*.json reference"}
    ref = _bench_result_from_file(ref_path)
    if ref is None:
        return {"status": "skipped: unparseable reference "
                          + os.path.basename(ref_path)}
    row = compare_step_time(fresh or RESULT, ref, pct)
    row["reference_artifact"] = os.path.basename(ref_path)
    return row


def regression_only(fresh_path: str) -> int:
    """``bench.py --regression-only <fresh.json>``: compare an EXISTING
    capture (e.g. the cycle's promoted bench JSON) against the newest
    ``BENCH_r*.json`` without re-running anything. Prints one JSON line;
    exit 1 on a confirmed >threshold step-time regression (callers treat it
    as a non-fatal probe row — see scripts/tpu_watch.sh)."""
    fresh = _bench_result_from_file(fresh_path)
    if fresh is None:
        row = {"status": f"skipped: unparseable fresh capture {fresh_path}"}
    else:
        row = step_time_regression(fresh=fresh)
    print(json.dumps({"metric": "bench_step_time_regression",
                      "value": row.get("delta_pct", 0.0),
                      "unit": "pct_step_time_delta",
                      "detail": row}))
    return 1 if row.get("fail") else 0


_DECODE_CHILD: dict = {}


def bench_quantized_comm(jax, on_tpu) -> dict:
    """ZeRO++ trio wire-volume probe (quantized & hierarchical collectives,
    docs/performance.md): trace-time CommsTelemetry byte accounting for —

    (a) the stage-2 param all-gather, qwZ off vs on: quantized wire bytes vs
        the fp32 equivalent of the same payload (the >=3.5x acceptance
        number comes from algo accounting, not from an assertion);
    (b) gas-composed DP volume: plain stage-2 per-micro reduction vs
        deferred-GAS + qgZ int8 grads + qwZ int8 weight gather.

    Tiny model, one real step per config — the records are per-trace, so
    this costs seconds on CPU and TPU alike."""
    import numpy as np
    import jax.numpy as jnp

    import deepspeed_tpu as dst
    from deepspeed_tpu.comm import comm as ds_comm
    from deepspeed_tpu.comm import mesh as mesh_lib
    from deepspeed_tpu.models import llama

    mcfg = llama.LlamaConfig.tiny(vocab_size=512, max_seq_len=64,
                                  use_pipeline=False)
    n_dev = max(1, len(jax.devices()))

    def run(zero, co=None, gas=1):
        mesh_lib.set_mesh(None)
        tel = ds_comm.get_telemetry()
        tel.reset()
        config = {
            "train_batch_size": 2 * n_dev * gas,
            "gradient_accumulation_steps": gas,
            "bf16": {"enabled": True},
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2, **zero},
            "comms_logger": {"enabled": True},
            "steps_per_print": 0,
        }
        if co:
            config["comms_overlap"] = co
        spec = llama.model_spec(mcfg, compute_dtype=jnp.bfloat16)
        engine, _, _, _ = dst.initialize(model=spec, config=config)
        tokens = np.random.default_rng(0).integers(
            0, mcfg.vocab_size, (engine.train_batch_size(), 33),
            dtype=np.int32)
        engine.train_batch({"tokens": tokens})
        summ = tel.summary()
        gather = {k: v for k, v in summ.items()
                  if k.startswith("all_gather_params")}
        return {
            "gather_wire_bytes": int(sum(s["bytes"] for s in
                                         gather.values())),
            "gather_fp32_equiv": int(sum(s["fp32_equiv_bytes"] for s in
                                         gather.values())),
            "total_algo_bytes": int(tel.total_algo_bytes()),
        }

    try:
        base = run({})
        qwz = run({"zero_quantized_weights": True})
        gas = 2
        dp_base = run({}, gas=gas)
        dp_q = run({"zero_quantized_weights": True,
                    "zero_quantized_gradients": True},
                   co={"enabled": True, "deferred_gradient_reduce": True,
                       "loco": True, "coalesce_buckets": False}, gas=gas)
        out = {
            "ok": True,
            "allgather": {
                "fp32_equiv_bytes": qwz["gather_fp32_equiv"],
                "wire_bytes_base": base["gather_wire_bytes"],
                "wire_bytes_qwz": qwz["gather_wire_bytes"],
                # wire reduction of the weight gather vs an fp32 wire
                "qwz_reduction_vs_fp32": round(
                    qwz["gather_fp32_equiv"]
                    / max(qwz["gather_wire_bytes"], 1), 2),
            },
            "dp_volume": {
                "gas": gas,
                "algo_bytes_base": dp_base["total_algo_bytes"],
                "algo_bytes_qgz_qwz_deferred": dp_q["total_algo_bytes"],
                "reduction": round(dp_base["total_algo_bytes"]
                                   / max(dp_q["total_algo_bytes"], 1), 2),
            },
        }
    except Exception as e:  # must never poison the headline number
        out = {"ok": False, "error": f"{type(e).__name__}: {e}"[-400:]}
    return out


def run_quant_comm(jax, on_tpu) -> dict:
    """:func:`bench_quantized_comm`, but on a single-device backend (the CPU
    fallback) there is no gather boundary to record — rerun the probe in a
    child on an 8-virtual-device CPU mesh so the wire accounting is real
    either way. Multi-device backends run in-process."""
    if len(jax.devices()) > 1:
        return bench_quantized_comm(jax, on_tpu)
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    try:
        out = subprocess.run([sys.executable, __file__, "--quant-comm-only"],
                             capture_output=True, text=True, timeout=560,
                             env=env)
        tail = [l for l in out.stdout.strip().splitlines()
                if l.startswith("QUANT_COMM=")]
        if out.returncode == 0 and tail:
            child = json.loads(tail[-1][len("QUANT_COMM="):])
            child["devices"] = "8-virtual-cpu (single-device parent)"
            return child
        return {"ok": False,
                "error": f"child rc={out.returncode} {out.stderr[-200:]}"}
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": "timeout: quant-comm child > 560s"}


def quant_comm_only():
    """Child entry for :func:`run_quant_comm` (env forces the 8-device
    virtual CPU mesh before jax initializes)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    print("QUANT_COMM=" + json.dumps(bench_quantized_comm(jax, False)))


def bench_tiered_mem(jax, on_tpu, steps: int = None) -> dict:
    """``detail.tiered_mem`` — the tiered-memory acceptance probe
    (docs/memory.md): (a) optimizer host-offload step time vs the in-HBM
    baseline on the SAME model, with the store's measured transfer-overlap
    fraction (``Memory/tier/overlap_frac``: the share of transfer wall time
    hidden under compute — the ≥0.5 acceptance) and the device-resident
    byte delta between steps (host-tier opt state leaves the device
    allocator); (b) KV host-spill restore latency: admission of a fully
    spilled prefix (restore path) vs a cold admission of the same prompt.
    Non-fatal: failures return status and never poison the headline."""
    import numpy as np

    try:
        import jax.numpy as jnp

        import deepspeed_tpu as dst
        from deepspeed_tpu.comm import mesh as mesh_lib
        from deepspeed_tpu.models import llama
        from deepspeed_tpu.telemetry.memory import MemoryTelemetry

        if steps is None:
            steps = 8 if on_tpu else 5
        mcfg = bench_model_config(on_tpu)
        seqlen = 512 if on_tpu else 128
        out: dict = {"ok": True}

        def run(tiered: bool):
            mesh_lib.set_mesh(None)
            config = {
                "train_batch_size": 8 * max(1, len(jax.devices())),
                "bf16": {"enabled": True},
                "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 0,
            }
            if tiered:
                config["memory"] = {"tiering": {"enabled": True,
                                                "optimizer_tier": "host"}}
            spec = llama.model_spec(mcfg, compute_dtype=jnp.bfloat16)
            engine, _, _, _ = dst.initialize(model=spec, config=config)
            rng = np.random.default_rng(0)

            def batch():
                return {"tokens": rng.integers(
                    0, mcfg.vocab_size,
                    (engine.train_batch_size(), seqlen + 1), dtype=np.int32)}

            float(engine.train_batch(batch()).loss)  # compile + warm
            t0 = time.perf_counter()
            for _ in range(steps):
                o = engine.train_batch(batch())
            float(o.loss)
            dt = (time.perf_counter() - t0) / steps
            import gc

            gc.collect()  # drop freed buffers before the live-array census
            resident = MemoryTelemetry().snapshot()["bytes_in_use"]
            return engine, dt, resident

        e0, dt0, res0 = run(False)
        opt_bytes = sum(getattr(l, "nbytes", 0)
                        for l in jax.tree.leaves(e0.state.opt_state))
        del e0
        e1, dt1, res1 = run(True)
        store = e1.tiered_store
        out["optimizer_offload"] = {
            "step_time_s_baseline": round(dt0, 4),
            "step_time_s_offload": round(dt1, 4),
            "slowdown": round(dt1 / dt0, 3) if dt0 > 0 else None,
            "opt_state_bytes": int(opt_bytes),
            "device_bytes_between_steps_baseline": int(res0),
            "device_bytes_between_steps_offload": int(res1),
            "device_bytes_delta": int(res0 - res1),
            "host_tier_resident_bytes": store.resident_bytes("host"),
            "overlap_frac": round(store.overlap_frac(), 3),
            "prefetch_hits": int(store.stats["prefetch_hits"]),
            "prefetch_misses": int(store.stats["prefetch_misses"]),
        }
        e1.destroy()
        del e1

        # --- (b) KV host-spill restore latency ---
        from deepspeed_tpu.inference.engine_v2 import build_engine_v2
        from deepspeed_tpu.inference.sampling import SamplingParams

        mesh_lib.set_mesh(None)
        icfg = llama.LlamaConfig.tiny(max_seq_len=256) if not on_tpu else mcfg
        params = llama.init(icfg, jax.random.PRNGKey(0))
        eng = build_engine_v2(
            llama, icfg, params,
            config={"dtype": "float32", "prefill_bucket": 16,
                    "prefix_cache": {"enabled": True,
                                     "max_retained_blocks": 2,
                                     "host_spill": True},
                    "ragged": {"max_tracked_sequences": 4,
                               "max_ragged_batch_size": 4,
                               "memory_config_blocks": 64,
                               "block_size": 16}})
        sp = SamplingParams(greedy=True)
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, icfg.vocab_size, (64,),
                                dtype=np.int32).tolist() for _ in range(3)]
        for i, p in enumerate(prompts):   # fill, decode, retire → spills
            eng.put(i, p, sp)
            eng.step(sp)
            eng.finish(i)
        # warm the restore path (the spill-write program compiles once)
        eng.put(80, prompts[1], sp)
        eng.step(sp)
        eng.finish(80)
        # cold admission (novel prompt) vs restore admission (spilled prefix)
        cold = rng.integers(0, icfg.vocab_size, (64,), dtype=np.int32).tolist()
        t0 = time.perf_counter()
        eng.put(90, cold, sp)
        eng.step(sp)
        t_cold = time.perf_counter() - t0
        eng.finish(90)
        t0 = time.perf_counter()
        eng.put(91, prompts[0], sp)       # restores spilled blocks
        eng.step(sp)
        t_restore = time.perf_counter() - t0
        eng.finish(91)
        st = eng.state.prefix_stats
        out["kv_spill"] = {
            "spills": int(st["spills"]),
            "restores": int(st["restores"]),
            "restored_tokens": int(st["restored_tokens"]),
            "admit_cold_s": round(t_cold, 4),
            "admit_restore_s": round(t_restore, 4),
            "restore_speedup": (round(t_cold / t_restore, 2)
                                if t_restore > 0 else None),
        }
        return out
    except Exception as e:
        return {"ok": False, "status": f"error: {e}"[-300:]}


def bench_integrity(jax, on_tpu, steps: int = None) -> dict:
    """``detail.integrity`` — fingerprint-plane overhead probe
    (docs/reliability.md "Numerics integrity & SDC"): the SAME model stepped
    with the numerics-integrity plane off vs on at ``check_interval=10``,
    reporting the step-time overhead fraction against the ≤2% acceptance
    budget. Also pins the default-OFF contract observable from here: the off
    run must emit zero ``Reliability/integrity/*`` events. ``ok`` gates on
    the event invariants only — the timing row is evidence, not a pass/fail
    (CPU-lane step times are too noisy for a 2% assertion)."""
    import numpy as np

    try:
        import jax.numpy as jnp

        import deepspeed_tpu as dst
        from deepspeed_tpu.comm import mesh as mesh_lib
        from deepspeed_tpu.models import llama

        if steps is None:
            # CPU-lane steps are ~7ms, so the first check round's one-time
            # host-path warmup needs more rounds to amortize out of the mean
            steps = 20 if on_tpu else 30
        mcfg = bench_model_config(on_tpu)
        seqlen = 512 if on_tpu else 128
        check_interval = 10
        steps = max(steps, check_interval)  # at least one check must fire

        def run(enabled: bool):
            mesh_lib.set_mesh(None)
            config = {
                "train_batch_size": 8 * max(1, len(jax.devices())),
                "bf16": {"enabled": True},
                "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 0,
            }
            if enabled:
                config["reliability"] = {"integrity": {
                    "enabled": True, "check_interval": check_interval}}
            spec = llama.model_spec(mcfg, compute_dtype=jnp.bfloat16)
            engine, _, _, _ = dst.initialize(model=spec, config=config)
            rng = np.random.default_rng(0)

            def batch():
                return {"tokens": rng.integers(
                    0, mcfg.vocab_size,
                    (engine.train_batch_size(), seqlen + 1), dtype=np.int32)}

            float(engine.train_batch(batch()).loss)  # compile + warm
            t0 = time.perf_counter()
            for _ in range(steps):
                o = engine.train_batch(batch())
            float(o.loss)
            dt = (time.perf_counter() - t0) / steps
            counts = {k: int(v) for k, v in
                      dict(getattr(engine.telemetry, "reliability_counts",
                                   {}) or {}).items()
                      if k.startswith("Reliability/integrity/")}
            engine.destroy()
            return dt, counts

        dt_off, ev_off = run(False)
        dt_on, ev_on = run(True)
        overhead = dt_on / dt_off - 1.0 if dt_off > 0 else None
        return {
            "ok": not ev_off and ev_on.get("Reliability/integrity/checks",
                                           0) > 0,
            "step_time_s_off": round(dt_off, 4),
            "step_time_s_on": round(dt_on, 4),
            "overhead_frac": (round(overhead, 4)
                              if overhead is not None else None),
            "budget_frac": 0.02,
            "within_budget": (overhead is not None and overhead <= 0.02),
            "check_interval": check_interval,
            "steps": steps,
            "events_off": ev_off,
            "events_on": ev_on,
        }
    except Exception as e:
        return {"ok": False, "status": f"error: {e}"[-300:]}


def bench_tuning(jax, on_tpu, steps: int = None) -> dict:
    """``detail.tuning`` — self-tuning runtime probe (docs/tuning.md):

    (a) **convergence oracle** (deterministic, fake clock): a synthetic
    knob whose score series is a planted function of the applied choice;
    the online tuner must find the planted optimum, persist it, and a
    fresh tuner must reload it with ZERO re-search trials — this row
    gates ``ok``;
    (b) **live-engine structural row**: a real engine with the ``tuning``
    block enabled on ``train.remat_policy`` (planted at the expensive
    ``full`` policy) stepped until the knob search closes — reports the
    measured per-arm scores, accept/revert/veto counters, and that no
    guard veto fired. Timing-dependent (CPU-lane step noise), so it is
    evidence, not a pass/fail."""
    import tempfile

    try:
        import numpy as np

        import deepspeed_tpu as dst
        from deepspeed_tpu.comm import mesh as mesh_lib
        from deepspeed_tpu.models import llama
        from deepspeed_tpu.telemetry.schema import validate_events
        from deepspeed_tpu.tuning import (OnlineTuner, Tunable,
                                          TunableRegistry, TunerOptions,
                                          load_tuned)

        out = {}
        with tempfile.TemporaryDirectory() as td:
            # -- (a) planted-optimum oracle, fully deterministic -------- #
            path = os.path.join(td, "tuned.json")
            reg = TunableRegistry([Tunable(
                "bench.lanes", "lanes", (1, 2, 4),
                "Serving/sched/goodput_frac", "max", "sched_tick",
                root="sched_config")])
            opts = TunerOptions(enabled=True, steps_per_arm=5,
                                min_samples=3, seed=0, path=path)
            goodput = {1: 0.55, 2: 0.72, 4: 0.91}   # planted: 4 wins

            class _NS:
                lanes = 1

            def drive(tuner, ns, clock_box, nsteps=40):
                for step in range(nsteps):
                    clock_box[0] += 1.0
                    tuner.observe(
                        "Serving/sched/goodput_frac",
                        goodput[ns.lanes]
                        + 0.004 * ((step * 7) % 5 - 2))  # deterministic noise
                    tuner.advance(step)

            ns, clock = _NS(), [0.0]
            tuner = OnlineTuner(reg, opts, boundary="sched_tick",
                                roots={"sched_config": ns},
                                clock=lambda: clock[0])
            drive(tuner, ns, clock)
            schema_problems = validate_events(tuner.events(step=40))
            ns2, clock2 = _NS(), [1000.0]
            fresh = OnlineTuner(reg, opts, boundary="sched_tick",
                                roots={"sched_config": ns2},
                                clock=lambda: clock2[0])
            out["oracle"] = {
                "planted_best": 4, "converged_to": ns.lanes,
                "persisted": load_tuned(path).get("bench.lanes"),
                "reloaded_value": ns2.lanes,
                "reload_trials": fresh.totals["trials"],
                "counts": dict(tuner.totals),
                "schema_problems": schema_problems,
            }
            oracle_ok = (ns.lanes == 4 and ns2.lanes == 4
                         and fresh.totals["trials"] == 0
                         and tuner.totals["vetoes"] == 0
                         and not schema_problems)

            # -- (b) live engine, remat knob planted suboptimal --------- #
            if steps is None:
                steps = 24
            mesh_lib.set_mesh(None)
            mcfg = bench_model_config(on_tpu)
            config = {
                "train_batch_size": 8 * max(1, len(jax.devices())),
                "bf16": {"enabled": True},
                "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
                "zero_optimization": {"stage": 2},
                "activation_checkpointing": {"policy": "full"},  # planted
                "steps_per_print": 0,
                "tuning": {"enabled": True,
                           "knobs": ["train.remat_policy"],
                           "steps_per_arm": 5, "min_samples": 3,
                           "max_dwell_factor": 2, "seed": 0,
                           "path": os.path.join(td, "engine_tuned.json")},
            }
            import jax.numpy as jnp

            spec = llama.model_spec(mcfg, compute_dtype=jnp.bfloat16)
            engine, _, _, _ = dst.initialize(model=spec, config=config)
            rng = np.random.default_rng(0)
            seqlen = 512 if on_tpu else 128

            def batch():
                return {"tokens": rng.integers(
                    0, mcfg.vocab_size,
                    (engine.train_batch_size(), seqlen + 1), dtype=np.int32)}

            for _ in range(steps):
                o = engine.train_batch(batch())
            float(o.loss)
            s = engine.tuning.summary()
            knob = s["knobs"]["train.remat_policy"]
            out["engine"] = {
                "planted": "full", "final_policy": knob["value"],
                "phase": knob["phase"], "counts": knob["counts"],
                "arm_scores_ms": {k: round(v * 1.0, 3)
                                  for k, v in knob["results"].items()},
                "steps": steps,
            }
            engine.destroy()
        out["ok"] = oracle_ok and out["engine"]["counts"]["vetoes"] == 0
        return out
    except Exception as e:
        return {"ok": False, "status": f"error: {e}"[-300:]}


def bench_long_context(jax, on_tpu) -> dict:
    """``detail.long_context`` — million-token-context memory probe
    (docs/performance.md "Million-token context"): (a) compiled-peak temp
    bytes of the full train step, dense logits vs ``sequence.tiled_loss``,
    at a context length where the dense [B, S, V] logits blow a fixed
    byte budget the tiled step fits inside — and the tiled step actually
    TRAINS at that length; (b) the tiled step's peak must scale ~linearly
    in S (the FPDT-pin convention: ratio ≲ shards, never ×V); (c) ring
    schedule evidence: zigzag per-rank causal block-pair counts are
    balanced where contiguous ones skew P:1, and the measured per-hop
    KV-transfer overlap fraction (``Comm/ring/overlap_frac``) is nonzero
    with pipelining ON and zero serialized. Non-fatal: failures return
    status and never poison the headline."""
    import numpy as np

    try:
        import jax.numpy as jnp

        import deepspeed_tpu as dst
        from deepspeed_tpu.comm import mesh as mesh_lib
        from deepspeed_tpu.models import llama
        from deepspeed_tpu.sequence.ring import (measure_ring_overlap,
                                                 ring_block_pair_counts)

        # logits-dominated shape: a big vocab makes the dense [B, S, V]
        # head the peak, while layers stay tiny enough for the CPU lane
        vocab = 65536 if not on_tpu else 131072
        s_small, s_big = (512, 2048) if not on_tpu else (4096, 16384)
        budget_mb = float(os.environ.get("DSTPU_BENCH_LONGCTX_BUDGET_MB",
                                         512 if not on_tpu else 4096))
        mcfg = llama.LlamaConfig(
            vocab_size=vocab, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=2, num_kv_heads=2,
            max_seq_len=s_big + 1, remat=True)
        out: dict = {"ok": True, "budget_mb": budget_mb,
                     "vocab": vocab, "seq_len": s_big}

        def mk_engine(seqlen, tiled):
            mesh_lib.set_mesh(None)
            config = {
                "train_batch_size": max(1, len(jax.devices())),
                "bf16": {"enabled": True},
                "optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 0,
            }
            if tiled:
                config["sequence"] = {"tiled_loss": True,
                                      "tiled_loss_shards": 16,
                                      "ring": {"layout": "zigzag",
                                               "overlap": True}}
            spec = llama.model_spec(mcfg, compute_dtype=jnp.bfloat16)
            engine, _, _, _ = dst.initialize(model=spec, config=config)
            return engine

        def temp_peak_mb(seqlen, tiled):
            """Compiled-peak temp bytes of the real train step — compile
            only, never executed (the dense step at s_big is the one we
            are proving does NOT fit)."""
            engine = mk_engine(seqlen, tiled)
            rng = np.random.default_rng(0)
            batch = {"tokens": rng.integers(
                0, vocab, (engine.train_batch_size(), seqlen + 1),
                dtype=np.int32)}
            if engine._train_step is None:
                engine._build_train_step()
            sb = engine._shard_batch(batch, with_gas_dim=True)
            with engine.mesh_mgr.activate():
                comp = engine._train_step.lower(
                    engine.state, sb, engine._lr_override).compile()
            mb = comp.memory_analysis().temp_size_in_bytes / 2**20
            engine.destroy()
            return mb

        dense_mb = temp_peak_mb(s_big, tiled=False)
        tiled_mb = temp_peak_mb(s_big, tiled=True)
        tiled_small_mb = temp_peak_mb(s_small, tiled=True)
        scale = s_big / s_small
        ratio = tiled_mb / max(tiled_small_mb, 1e-9)
        out["compiled_peak"] = {
            "dense_mb": round(dense_mb, 1),
            "tiled_mb": round(tiled_mb, 1),
            "dense_over_budget": dense_mb > budget_mb,
            "tiled_within_budget": tiled_mb <= budget_mb,
            "tiled_mb_at_quarter_seq": round(tiled_small_mb, 1),
            "tiled_scaling_ratio": round(ratio, 2),
            # linear ≈ scale; a dense head would add the ×(V/shards) cliff
            "tiled_scaling_linear": ratio < 2 * scale,
        }

        # the length the dense step cannot budget-fit must actually train
        engine = mk_engine(s_big, tiled=True)
        rng = np.random.default_rng(1)

        def batch():
            return {"tokens": rng.integers(
                0, vocab, (engine.train_batch_size(), s_big + 1),
                dtype=np.int32)}

        losses = [float(engine.train_batch(batch()).loss) for _ in range(2)]
        out["trains_at_dense_oom_len"] = {
            "losses": [round(l, 4) for l in losses],
            "finite": all(np.isfinite(losses)),
        }
        engine.destroy()

        # (c) ring schedule evidence — pure schedule math + the host-level
        # per-hop overlap measurement (writes Comm/ring/overlap_frac)
        p = 8
        zz = ring_block_pair_counts(p, "zigzag", causal=True)
        ct = ring_block_pair_counts(p, "contiguous", causal=True)
        ov_on = measure_ring_overlap(overlap=True, seq=2048)
        ov_off = measure_ring_overlap(overlap=False, seq=2048)
        out["ring"] = {
            "p_size": p,
            "zigzag_pair_counts": zz,
            "contiguous_pair_counts": ct,
            "zigzag_balanced": len(set(zz)) == 1,
            "contiguous_skew": max(ct) / max(min(ct), 1),
            "overlap_frac_on": round(ov_on["overlap_frac"], 3),
            "overlap_frac_off": round(ov_off["overlap_frac"], 3),
            "overlap_measured": ov_on["overlap_frac"] > 0.0,
        }
        out["ok"] = (out["compiled_peak"]["dense_over_budget"]
                     and out["compiled_peak"]["tiled_within_budget"]
                     and out["compiled_peak"]["tiled_scaling_linear"]
                     and out["trains_at_dense_oom_len"]["finite"]
                     and out["ring"]["zigzag_balanced"]
                     and out["ring"]["overlap_measured"])
        return out
    except Exception as e:
        return {"ok": False, "status": f"error: {e}"[-300:]}


def run_decode_subprocess() -> object:
    """Decode bench in a SUBPROCESS with a hard timeout, BEFORE this process
    initializes its own jax client: a wedged tunnel compile must never hold
    the headline JSON hostage (observed: >25 min hang in the paged-decode
    warmup), and on exclusive-access TPU runtimes a child started after the
    parent attaches could never get the device. The Popen handle is kept so
    the SIGTERM handler can kill the child too (exclusive chip, no orphans)."""
    import subprocess

    proc = subprocess.Popen([sys.executable, __file__, "--decode-only"],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    _DECODE_CHILD["proc"] = proc
    try:
        out, err = proc.communicate(timeout=600)
        tail = out.strip().splitlines()[-1] if out.strip() else ""
        if proc.returncode == 0 and tail.startswith("DECODE_TOK_PER_SEC="):
            val, child_backend = tail.split("=")[1].split()
            return {"value": float(val), "backend": child_backend}
        return f"failed: rc={proc.returncode} {err[-200:]}"
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        return "timeout: decode child exceeded 600s"
    finally:
        _DECODE_CHILD.pop("proc", None)


def install_term_handler():
    """Emit the partial RESULT on SIGTERM (watcher `timeout -k` kill) so a
    wall-clock overrun still ships whatever was measured — same contract as
    every probe script's _probe_common.install_term_handler."""
    import signal

    def on_term(signum, frame):
        child = _DECODE_CHILD.get("proc")
        if child is not None and child.poll() is None:
            child.kill()  # the chip is exclusive-access; no orphans
        RESULT["detail"]["interrupted"] = "SIGTERM (watcher timeout)"
        emit(ok=False)
        sys.exit(0)

    signal.signal(signal.SIGTERM, on_term)


def main():
    install_term_handler()
    probe_backend()  # one probe pass; children inherit the verdict via env
    decode = run_decode_subprocess()
    jax = init_backend()
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu as dst
    from deepspeed_tpu.models import llama

    on_tpu = "tpu" in RESULT["detail"].get("backend", "")
    mcfg = bench_model_config(on_tpu, remat=True)
    if on_tpu:
        batch, seqlen, steps, warmup = 8, 2048, 20, 3
    else:
        batch, seqlen, steps, warmup = 8, 128, 5, 1

    config = {
        "train_batch_size": batch * max(1, len(jax.devices())),
        "bf16": {"enabled": True},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": 3},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
        # trace-time comm accounting (free at run time): the per-step
        # collective count + algorithmic bytes land in detail so comm-volume
        # regressions are visible from the headline artifact
        "comms_logger": {"enabled": True},
    }
    sys.stderr.write(f"[bench] t={time.perf_counter():.0f} building engine\n")
    spec = llama.model_spec(mcfg, compute_dtype=jnp.bfloat16)
    engine, _, _, _ = dst.initialize(model=spec, config=config)

    rng = np.random.default_rng(0)

    def make_batch(i):
        return {"tokens": rng.integers(0, mcfg.vocab_size,
                                       (engine.train_batch_size(), seqlen + 1),
                                       dtype=np.int32)}

    sys.stderr.write(f"[bench] t={time.perf_counter():.0f} engine ready, warmup\n")
    for i in range(warmup):
        out = engine.train_batch(make_batch(i))
        float(out.loss)  # host sync (block_until_ready is a no-op on axon)
        sys.stderr.write(f"[bench] t={time.perf_counter():.0f} warmup {i} done loss={float(out.loss):.3f}\n")

    t0 = time.perf_counter()
    for i in range(steps):
        out = engine.train_batch(make_batch(warmup + i))
    final_loss = float(out.loss)  # drains the async dispatch queue
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    tokens_per_step = engine.train_batch_size() * seqlen
    tokens_per_sec_per_chip = tokens_per_step * steps / dt / n_chips
    n_params = mcfg.num_params
    flops_per_token = model_flops_per_token(mcfg, seqlen)
    mfu = tokens_per_sec_per_chip * flops_per_token / peak_flops_per_chip(jax)

    RESULT["value"] = round(mfu, 4)
    RESULT["vs_baseline"] = round(mfu / 0.45, 4)
    RESULT["detail"].update({
        "tokens_per_sec_per_chip": round(tokens_per_sec_per_chip, 1),
        "step_time_s": round(dt / steps, 4),
        "params": n_params,
        "batch": engine.train_batch_size(),
        "seqlen": seqlen,
        "final_loss": final_loss,
    })
    try:  # per-step comm volume of the compiled step (trace-time records)
        from deepspeed_tpu.comm import comm as ds_comm

        tel = ds_comm.get_telemetry()
        if tel.records:
            total_algo = tel.total_algo_bytes()
            RESULT["detail"]["comm_per_step"] = {
                "collectives": int(sum(s["count"]
                                       for s in tel.summary().values())),
                "algo_bytes": int(total_algo),
                "busbw_gbps": round(total_algo / (dt / steps) / 1e9, 2),
            }
    except Exception:
        pass  # comm accounting must never fail the headline
    # 8B-class shape rows (TPU only — each is a multi-minute compile; the
    # persistent cache makes re-runs cheap). Forced via DSTPU_BENCH_SHAPES=1.
    if on_tpu or os.environ.get("DSTPU_BENCH_SHAPES", "0") not in ("", "0"):
        del engine  # free the headline engine's state before the sweep
        RESULT["detail"]["shape_mfu"] = bench_shape_rows(jax)

    # standalone attention MFU at hd=128/bq=512 (PERF.md open item),
    # the per-remat-policy HBM-vs-step-time sweep, and the combined
    # overlap+selective-remat vs full-remat comparison — all captured by
    # scripts/tpu_watch.sh through this headline bench. Skippable for
    # narrow-budget runs via DSTPU_BENCH_REMAT=0.
    if os.environ.get("DSTPU_BENCH_REMAT", "1") not in ("", "0"):
        RESULT["detail"]["attn_probe"] = bench_attention_probe(jax)
        RESULT["detail"]["remat_sweep"] = bench_remat_sweep(jax, on_tpu)
        RESULT["detail"]["overlap_remat"] = bench_overlap_remat(jax, on_tpu)

    # ZeRO++ trio wire-volume accounting (qwZ all-gather compression, gas-
    # composed qgZ+qwZ DP volume) — trace-time byte records, seconds to run.
    # Skippable via DSTPU_BENCH_QCOMM=0.
    if os.environ.get("DSTPU_BENCH_QCOMM", "1") not in ("", "0"):
        RESULT["detail"]["quant_comm"] = run_quant_comm(jax, on_tpu)

    # tiered-memory acceptance probe (docs/memory.md): optimizer host-
    # offload step time + measured transfer-overlap fraction vs the in-HBM
    # baseline, and KV host-spill restore latency. Non-fatal; skippable via
    # DSTPU_BENCH_TIERED=0.
    if os.environ.get("DSTPU_BENCH_TIERED", "1") not in ("", "0"):
        RESULT["detail"]["tiered_mem"] = bench_tiered_mem(jax, on_tpu)

    # numerics-integrity plane overhead probe (docs/reliability.md "Numerics
    # integrity & SDC"): step time with cross-replica fingerprints off vs on
    # at check_interval=10 against the ≤2% budget, plus the default-OFF
    # zero-events pin. Non-fatal; skippable via DSTPU_BENCH_INTEGRITY=0.
    if os.environ.get("DSTPU_BENCH_INTEGRITY", "1") not in ("", "0"):
        RESULT["detail"]["integrity"] = bench_integrity(jax, on_tpu)

    # million-token-context memory probe (docs/performance.md "Million-token
    # context"): dense-logits vs tiled-loss compiled peaks against a byte
    # budget, the tiled step training at the dense-over-budget length, and
    # the ring zigzag-balance + measured overlap evidence. Non-fatal;
    # skippable via DSTPU_BENCH_LONGCTX=0.
    if os.environ.get("DSTPU_BENCH_LONGCTX", "1") not in ("", "0"):
        RESULT["detail"]["long_context"] = bench_long_context(jax, on_tpu)

    # self-tuning runtime probe (docs/tuning.md): deterministic planted-
    # optimum convergence + persist/reload oracle (gates the row's ok), and
    # a live-engine remat-knob search with guard counters. Non-fatal;
    # skippable via DSTPU_BENCH_TUNING=0.
    if os.environ.get("DSTPU_BENCH_TUNING", "1") not in ("", "0"):
        RESULT["detail"]["tuning"] = bench_tuning(jax, on_tpu)

    # step-time regression vs the newest checked-in BENCH_r*.json —
    # informational here (the gating form is --regression-only, wired as a
    # non-fatal tpu_watch.sh probe row). Skippable via DSTPU_BENCH_REGRESSION=0.
    if os.environ.get("DSTPU_BENCH_REGRESSION", "1") not in ("", "0"):
        try:
            RESULT["detail"]["regression"] = step_time_regression()
        except Exception as e:  # a trajectory note must never kill the run
            RESULT["detail"]["regression"] = {"status": f"error: {e}"[-200:]}

    # a decode child that fell back to CPU must not masquerade as the
    # accelerator decode number
    if isinstance(decode, dict):
        if decode["backend"] == RESULT["detail"].get("backend"):
            RESULT["detail"]["decode_tok_per_sec"] = decode["value"]
        else:
            RESULT["detail"]["decode_tok_per_sec"] = \
                f"skipped: child backend={decode['backend']}"
    else:
        RESULT["detail"]["decode_tok_per_sec"] = decode
    emit(ok=True)


def decode_only():
    probe_backend(attempts=1)  # no-op when the parent already probed
    jax = init_backend()
    import jax.numpy as jnp  # noqa: F401  (backend must be up first)

    backend = RESULT["detail"].get("backend", "")
    mcfg = bench_model_config("tpu" in backend)
    print(f"DECODE_TOK_PER_SEC={bench_decode(jax, mcfg)} {backend}")


def bench_decode(jax, mcfg, batch: int = 16, prompt_len: int = None,
                 decode_steps: int = None) -> float:
    """Continuous-batching decode throughput (paged Pallas kernel path) —
    tokens/sec across the batch at steady state. Sizes scale from the model's
    max_seq_len so the CPU-fallback tiny config fits its block tables."""
    import numpy as np

    if prompt_len is None:
        prompt_len = min(128, mcfg.max_seq_len // 4)
    if decode_steps is None:
        decode_steps = min(64, mcfg.max_seq_len // 2 - prompt_len - 1)

    from deepspeed_tpu.comm import mesh as mesh_lib
    from deepspeed_tpu.inference.engine_v2 import build_engine_v2
    from deepspeed_tpu.inference.sampling import SamplingParams
    from deepspeed_tpu.models import llama

    mesh_lib.set_mesh(None)
    params = llama.init(mcfg, jax.random.PRNGKey(0))
    eng = build_engine_v2(
        llama, mcfg, params,
        config={"dtype": "bfloat16", "prefill_bucket": prompt_len,
                "ragged": {"max_tracked_sequences": batch,
                           "max_ragged_batch_size": batch,
                           "memory_config_blocks": batch * 24,
                           "block_size": 32}})
    rng = np.random.default_rng(0)
    sp = SamplingParams(greedy=True)
    for uid in range(batch):
        eng.put(uid, rng.integers(0, mcfg.vocab_size, (prompt_len,),
                                  dtype=np.int32).tolist(), sp)
    # fused quantum (step_many): one host sync per `q` tokens — through the
    # tunnel a per-token sync dominates decode (r2: the per-step probe blew
    # its 600s budget); this is also the serving fast path on real silicon
    q = max(1, min(8, decode_steps))
    eng.step_many(q, sp)  # compile + warm
    done = 0
    t0 = time.perf_counter()
    while done < batch * decode_steps:
        out = eng.step_many(q, sp)  # host-int return: call is synchronized
        produced = sum(len(v) for v in out.values())
        if produced == 0:
            break  # context capacity reached — never count no-op calls
        done += produced
    dt = time.perf_counter() - t0
    return round(done / dt, 1)


if __name__ == "__main__":
    if "--decode-only" in sys.argv:
        decode_only()
        sys.exit(0)
    if "--quant-comm-only" in sys.argv:
        quant_comm_only()
        sys.exit(0)
    if "--regression-only" in sys.argv:
        idx = sys.argv.index("--regression-only")
        if idx + 1 >= len(sys.argv):
            print("usage: bench.py --regression-only <fresh_bench.json>",
                  file=sys.stderr)
            sys.exit(2)
        sys.exit(regression_only(sys.argv[idx + 1]))
    try:
        main()
    except Exception:
        emit(ok=False, err=traceback.format_exc())
        sys.exit(0)  # the JSON line IS the report; never rc!=0 without one
