#!/usr/bin/env python
"""Headline benchmark: Llama-style causal-LM training step throughput + MFU.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Baseline (BASELINE.md): the reference's ZeRO-3 north-star is >=45% MFU; we
report our measured model-flops-utilization against that target. Runs on
whatever jax.devices() provides (the real TPU chip under the driver; CPU
elsewhere, where the number is only a smoke signal).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def peak_flops_per_chip() -> float:
    """bf16 peak for the local accelerator."""
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 2e12  # CPU smoke-run placeholder


def main():
    import deepspeed_tpu as dst
    from deepspeed_tpu.models import llama

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        mcfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=3584,
            num_layers=12, num_heads=16, num_kv_heads=8, max_seq_len=2048,
            rope_theta=500000.0, remat=True)
        batch, seqlen, steps, warmup = 8, 2048, 20, 3
    else:
        mcfg = llama.LlamaConfig.tiny()
        batch, seqlen, steps, warmup = 8, 128, 5, 1

    config = {
        "train_batch_size": batch * max(1, len(jax.devices())),
        "bf16": {"enabled": True},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": 3},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
    }
    spec = llama.model_spec(mcfg, compute_dtype=jnp.bfloat16)
    engine, _, _, _ = dst.initialize(model=spec, config=config)

    rng = np.random.default_rng(0)
    def make_batch(i):
        return {"tokens": rng.integers(0, mcfg.vocab_size,
                                       (engine.train_batch_size(), seqlen + 1),
                                       dtype=np.int32)}

    for i in range(warmup):
        out = engine.train_batch(make_batch(i))
        float(out.loss)  # host sync (block_until_ready is a no-op on axon)

    t0 = time.perf_counter()
    for i in range(steps):
        out = engine.train_batch(make_batch(warmup + i))
    final_loss = float(out.loss)  # drains the async dispatch queue
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    tokens_per_step = engine.train_batch_size() * seqlen
    tokens_per_sec_per_chip = tokens_per_step * steps / dt / n_chips
    # model flops: 6*N per token (fwd+bwd) + attention term 12*L*H*S per token
    n_params = mcfg.num_params
    attn_flops_per_token = 12 * mcfg.num_layers * mcfg.hidden_size * seqlen
    flops_per_token = 6 * n_params + attn_flops_per_token
    mfu = tokens_per_sec_per_chip * flops_per_token / peak_flops_per_chip()

    print(json.dumps({
        "metric": "llama_zero3_train_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.45, 4),
        "detail": {
            "tokens_per_sec_per_chip": round(tokens_per_sec_per_chip, 1),
            "step_time_s": round(dt / steps, 4),
            "params": n_params,
            "batch": engine.train_batch_size(),
            "seqlen": seqlen,
            "n_chips": n_chips,
            "backend": jax.default_backend(),
            "final_loss": final_loss,
        },
    }))


if __name__ == "__main__":
    main()
